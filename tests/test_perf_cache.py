"""Content-addressed verdict/encode caches + pipelined scan (ISSUE 5).

The contract under test: cached and pipelined scans are BIT-IDENTICAL
to the serial uncached path — under resource mutation, policy-set
revision bumps, ns-label changes, context-dep movement, injected
dispatch faults, and LRU pressure — and a repeat scan of an unchanged
resource set serves >=90% of verdicts from the cache.
"""

import numpy as np

from kyverno_tpu.api.policy import ClusterPolicy
from kyverno_tpu.observability.metrics import global_registry as reg
from kyverno_tpu.tpu.cache import (LruCache, VerdictCache,
                                   enable_xla_compile_cache,
                                   global_encode_cache, global_verdict_cache,
                                   request_digest, resource_content_hash)
from kyverno_tpu.tpu.engine import TpuEngine


def _pol(name="p1", field="privileged", value="false"):
    return ClusterPolicy.from_dict({
        "apiVersion": "kyverno.io/v1", "kind": "ClusterPolicy",
        "metadata": {"name": name},
        "spec": {"rules": [{
            "name": "r1",
            "match": {"any": [{"resources": {"kinds": ["Pod"]}}]},
            "validate": {"message": "m", "pattern": {"spec": {"containers": [
                {"=(securityContext)": {f"=({field})": value}}]}}},
        }]}})


def _pods(n, priv_every=3, ns="default"):
    return [{
        "apiVersion": "v1", "kind": "Pod",
        "metadata": {"name": f"p{i}", "namespace": ns, "uid": f"u-{ns}-{i}"},
        "spec": {"containers": [{
            "name": "c", "image": "nginx",
            **({"securityContext": {"privileged": True}}
               if i % priv_every == 0 else {})}]},
    } for i in range(n)]


def _hits(d=0.0):
    return reg.verdict_cache.value({"outcome": "hit"}) - d


def _misses(d=0.0):
    return reg.verdict_cache.value({"outcome": "miss"}) - d


# ---------------------------------------------------------------------------
# LRU primitive


def test_lru_bound_and_eviction_order():
    lru = LruCache(3)
    for k in "abc":
        lru.put(k, k.upper())
    assert len(lru) == 3 and lru.evictions == 0
    lru.get("a")          # refresh: 'b' is now the oldest
    lru.put("d", "D")
    assert lru.evictions == 1
    assert lru.get("b") is None          # evicted
    assert lru.get("a") == "A" and lru.get("d") == "D"
    lru.set_capacity(1)                  # shrink evicts down to bound
    assert len(lru) == 1 and lru.evictions == 3
    lru.set_capacity(0)                  # 0 disables entirely
    lru.put("x", "X")
    assert lru.get("x") is None and len(lru) == 0


def test_verdict_cache_lru_bound_and_metrics():
    vc = VerdictCache(capacity=4, metrics=reg)
    ev0 = reg.verdict_cache_evictions.value()
    for i in range(8):
        vc.put(("k", i), np.full(3, i, dtype=np.int32))
    assert len(vc) == 4
    assert reg.verdict_cache_evictions.value() - ev0 == 4
    col = vc.get(("k", 7))
    assert col.tolist() == [7, 7, 7]
    col[0] = 99                           # caller copies never alias
    assert vc.get(("k", 7)).tolist() == [7, 7, 7]
    assert vc.get(("k", 0)) is None       # evicted


# ---------------------------------------------------------------------------
# verdict cache: bit-identity + content invalidation


def test_cached_scan_bit_identical_and_hits():
    eng = TpuEngine([_pol()])
    assert eng.cache_eligible
    pods = _pods(12)
    first = eng.scan(pods)
    h0, m0 = _hits(), _misses()
    second = eng.scan(pods)
    assert np.array_equal(first.verdicts, second.verdicts)
    assert _hits(h0) == 12 and _misses(m0) == 0
    # the cached result equals the serial uncached oracle exactly
    oracle = eng._scan_uncached(pods)
    assert np.array_equal(second.verdicts, oracle.verdicts)


def test_resource_mutation_invalidates_only_that_resource():
    eng = TpuEngine([_pol()])
    pods = _pods(10)
    eng.scan(pods)
    mutated = [dict(p) for p in pods]
    mutated[4] = {**pods[4], "spec": {"containers": [{
        "name": "c", "image": "nginx",
        "securityContext": {"privileged": True}}]}}
    h0, m0 = _hits(), _misses()
    res = eng.scan(mutated)
    assert _misses(m0) == 1 and _hits(h0) == 9
    assert np.array_equal(res.verdicts,
                          eng._scan_uncached(mutated).verdicts)


def test_policy_revision_bump_invalidates():
    pods = _pods(6)
    eng1 = TpuEngine([_pol(value="false")])
    eng1.scan(pods)
    # same policy NAME, different content -> different policy-set key
    eng2 = TpuEngine([_pol(value="true")])
    h0, m0 = _hits(), _misses()
    res = eng2.scan(pods)
    assert _misses(m0) == 6 and _hits(h0) == 0
    assert np.array_equal(res.verdicts,
                          eng2._scan_uncached(pods).verdicts)
    # and the original engine's entries are still live (no flush)
    h0 = _hits()
    eng1.scan(pods)
    assert _hits(h0) == 6


def test_ns_label_change_invalidates():
    pods = _pods(5)
    eng = TpuEngine([_pol()])
    eng.scan(pods, namespace_labels={"default": {"team": "a"}})
    h0, m0 = _hits(), _misses()
    eng.scan(pods, namespace_labels={"default": {"team": "b"}})
    assert _misses(m0) == 5 and _hits(h0) == 0
    h0 = _hits()
    eng.scan(pods, namespace_labels={"default": {"team": "a"}})
    assert _hits(h0) == 5


def test_operation_and_userinfo_are_part_of_the_key():
    from kyverno_tpu.engine.match import RequestInfo

    pods = _pods(3)
    eng = TpuEngine([_pol()])
    eng.scan(pods, operations=["CREATE"] * 3)
    m0 = _misses()
    eng.scan(pods, operations=["UPDATE"] * 3)
    assert _misses(m0) == 3
    m0 = _misses()
    eng.scan(pods, operations=["CREATE"] * 3,
             admission_infos=[RequestInfo(username="eve")] * 3)
    assert _misses(m0) == 3


def test_context_dep_movement_rotates_the_policyset_key():
    """A configmap folded into the compiled program at compile time is
    part of the policy-set identity: recompiling after the configmap
    moved yields a different cache key, so stale verdicts are
    unreachable by construction."""
    from kyverno_tpu.engine.contextloaders import DataSources
    from kyverno_tpu.tpu.compiler import compile_policy_set

    pol = ClusterPolicy.from_dict({
        "apiVersion": "kyverno.io/v1", "kind": "ClusterPolicy",
        "metadata": {"name": "cm-pol"},
        "spec": {"rules": [{
            "name": "r1",
            "match": {"any": [{"resources": {"kinds": ["Pod"]}}]},
            "context": [{"name": "cm", "configMap": {
                "name": "limits", "namespace": "default"}}],
            "validate": {"message": "m", "deny": {"conditions": {"any": [{
                "key": "{{ cm.data.mode }}",
                "operator": "Equals", "value": "deny"}]}}},
        }]}})

    class _CM:
        def __init__(self, mode):
            self.mode = mode

        def get(self, key):
            return {"apiVersion": "v1", "kind": "ConfigMap",
                    "metadata": {"name": "limits", "namespace": "default"},
                    "data": {"mode": self.mode}}

    cps_a = compile_policy_set([pol],
                               data_sources=DataSources(configmaps=_CM("allow")))
    cps_b = compile_policy_set([pol],
                               data_sources=DataSources(configmaps=_CM("deny")))
    assert cps_a.context_deps and cps_b.context_deps
    assert cps_a.cache_key() != cps_b.cache_key()
    # and with identical content the keys agree (no spurious churn)
    cps_a2 = compile_policy_set([pol],
                                data_sources=DataSources(configmaps=_CM("allow")))
    assert cps_a.cache_key() == cps_a2.cache_key()


def test_dyn_slot_sets_are_cache_ineligible():
    """Rules whose context resolves per request (no compile-time
    folding) do real I/O — they must bypass the verdict cache."""
    pol = ClusterPolicy.from_dict({
        "apiVersion": "kyverno.io/v1", "kind": "ClusterPolicy",
        "metadata": {"name": "ctx-pol"},
        "spec": {"rules": [{
            "name": "r1",
            "match": {"any": [{"resources": {"kinds": ["Pod"]}}]},
            "context": [{"name": "cm", "configMap": {
                "name": "limits", "namespace": "default"}}],
            "validate": {"message": "m", "deny": {"conditions": {"any": [{
                "key": "{{ cm.data.mode }}",
                "operator": "Equals", "value": "deny"}]}}},
        }]}})
    eng = TpuEngine([pol])  # no data_sources: rule is host fallback
    assert not eng.cache_eligible
    assert eng.verdict_cache_keys(_pods(2)) is None
    b0 = reg.verdict_cache.value({"outcome": "bypass"})
    eng.scan(_pods(2))
    assert reg.verdict_cache.value({"outcome": "bypass"}) - b0 == 1


def test_unhashable_resource_bypasses_but_still_scans():
    eng = TpuEngine([_pol()])
    hostile = {"kind": b"bytes", "metadata": {"name": "h"}}
    pods = _pods(2) + [hostile]
    res = eng.scan(pods)
    assert res.verdicts.shape[1] == 3
    # repeat: the two clean pods hit, the hostile one re-evaluates
    h0 = _hits()
    res2 = eng.scan(pods)
    assert _hits(h0) == 2
    assert np.array_equal(res.verdicts, res2.verdicts)


# ---------------------------------------------------------------------------
# encode-row cache


def test_encode_row_cache_roundtrip_bit_identical():
    eng = TpuEngine([_pol()])
    pods = _pods(8)
    # force the verdict cache off so the second scan re-encodes (and
    # must restore rows from the encode cache)
    cap = global_verdict_cache._lru.capacity
    global_verdict_cache.set_capacity(0)
    try:
        a = eng.encode(pods)[0]
        eh0 = reg.encode_cache.value({"outcome": "hit"})
        b = eng.encode(pods)[0]
        assert reg.encode_cache.value({"outcome": "hit"}) - eh0 == 8
        for k in a:
            np.testing.assert_array_equal(a[k], b[k], err_msg=k)
        r1 = eng.scan(pods)
        r2 = eng.scan(pods)
        assert np.array_equal(r1.verdicts, r2.verdicts)
    finally:
        global_verdict_cache.set_capacity(cap)


def test_encode_cache_survives_policy_revision_bump():
    """The encode key covers encode caps + byte paths, NOT policy
    content: a revision bump misses the verdict cache but still skips
    the Python re-encode of unchanged resources."""
    pods = _pods(6)
    eng1 = TpuEngine([_pol(value="false")])
    eng1.scan(pods)
    eng2 = TpuEngine([_pol(value="true")])  # same encode shape, new content
    eh0 = reg.encode_cache.value({"outcome": "hit"})
    eng2.scan(pods)
    assert reg.encode_cache.value({"outcome": "hit"}) - eh0 >= 6


def test_encode_cache_disabled_matches_enabled():
    eng = TpuEngine([_pol()])
    pods = _pods(5)
    enabled = eng.encode(pods)[0]
    cap = global_encode_cache._lru.capacity
    global_encode_cache.set_capacity(0)
    try:
        disabled = eng.encode(pods)[0]
    finally:
        global_encode_cache.set_capacity(cap)
    for k in enabled:
        np.testing.assert_array_equal(enabled[k], disabled[k], err_msg=k)


# ---------------------------------------------------------------------------
# pipelined scan


def _sharded(policies):
    from kyverno_tpu.parallel import ShardedScanner, make_mesh

    return ShardedScanner(policies, mesh=make_mesh())


def test_pipelined_scan_bit_identical_to_serial():
    from kyverno_tpu.tpu.pipeline import PipelinedScanner

    sc = _sharded([_pol()])
    pods = _pods(40) + _pods(10, ns="prod")
    serial = sc.scan(pods)
    pipe = PipelinedScanner(sc)
    out = {}
    stats = pipe.scan_chunks([pods[i:i + 16] for i in range(0, len(pods), 16)],
                             on_result=lambda i, r: out.__setitem__(i, r))
    got = np.concatenate([out[i].verdicts for i in sorted(out)], axis=1)
    assert np.array_equal(serial.verdicts, got)
    assert stats["chunks"] == 4 and stats["resources"] == 50
    assert reg.pipeline_chunks.value({"path": "device"}) > 0


def test_pipelined_scan_bit_identical_under_dispatch_faults(no_verdict_cache):
    from kyverno_tpu.resilience.breaker import tpu_breaker
    from kyverno_tpu.resilience.faults import global_faults
    from kyverno_tpu.tpu.pipeline import PipelinedScanner

    sc = _sharded([_pol()])
    pods = _pods(32)
    serial = sc.scan(pods)
    global_faults.arm("tpu.dispatch", mode="raise", p=0.5, seed=11)
    try:
        pipe = PipelinedScanner(sc)
        out = {}
        pipe.scan_chunks([pods[i:i + 8] for i in range(0, 32, 8)],
                         on_result=lambda i, r: out.__setitem__(i, r))
        got = np.concatenate([out[i].verdicts for i in sorted(out)], axis=1)
        assert np.array_equal(serial.verdicts, got)
    finally:
        global_faults.disarm()
        tpu_breaker().reset()


def test_pipelined_scan_encode_failure_falls_back_not_aborts():
    from kyverno_tpu.tpu.pipeline import PipelinedScanner

    sc = _sharded([_pol()])
    hostile = {"kind": b"bytes-break-encoding", "metadata": {"name": "h"}}
    pods = _pods(8)
    chunks = [pods[:4], pods[4:] + [hostile]]
    pipe = PipelinedScanner(sc)
    out = {}
    stats = pipe.scan_chunks(chunks,
                             on_result=lambda i, r: out.__setitem__(i, r))
    assert stats["encode_fallback_chunks"] == 1
    assert out[1].verdicts.shape[1] == 5
    # clean chunk verdicts match the serial oracle
    serial = sc.scan(pods[:4])
    assert np.array_equal(out[0].verdicts, serial.verdicts)


# ---------------------------------------------------------------------------
# scan service: repeat-scan hit rate + churn invalidation


def test_repeat_scan_serves_90pct_from_cache():
    from kyverno_tpu.cluster import (BackgroundScanService, ClusterSnapshot,
                                     PolicyCache)

    snap = ClusterSnapshot()
    cache = PolicyCache()
    cache.set(_pol())
    svc = BackgroundScanService(snap, cache)
    for p in _pods(30):
        snap.upsert(p)
    assert svc.scan_once(full=True) == 30
    h0, m0 = _hits(), _misses()
    n = svc.scan_once(full=True)
    assert n == 30
    hits = _hits(h0)
    assert hits >= 0.9 * n, f"only {hits}/{n} served from cache"
    assert _misses(m0) == 0
    assert svc.stats["verdict_cache_hits"] >= 27
    # verdicts identical across the cached rescan
    report_a = svc.aggregator.summary()
    svc.scan_once(full=True)
    assert svc.aggregator.summary() == report_a


def test_policy_churn_invalidates_scan_cache():
    from kyverno_tpu.cluster import (BackgroundScanService, ClusterSnapshot,
                                     PolicyCache)

    snap = ClusterSnapshot()
    cache = PolicyCache()
    cache.set(_pol(value="false"))
    svc = BackgroundScanService(snap, cache)
    for p in _pods(10):
        snap.upsert(p)
    svc.scan_once(full=True)
    cache.set(_pol(value="true"))  # revision bump, new content
    h0, m0 = _hits(), _misses()
    svc.scan_once(full=True)
    assert _misses(m0) == 10 and _hits(h0) == 0


# ---------------------------------------------------------------------------
# admission submit-path cache


def test_admission_submit_serves_repeat_manifest_from_cache():
    import time

    from kyverno_tpu.cluster import PolicyCache
    from kyverno_tpu.engine.match import RequestInfo
    from kyverno_tpu.webhooks import build_handlers
    from kyverno_tpu.webhooks.server import AdmissionPayload

    cache = PolicyCache()
    cache.set(_pol())
    h = build_handlers(cache, batching=True)
    h.lifecycle.start()
    try:
        deadline = time.monotonic() + 120
        while h.lifecycle.active is None and time.monotonic() < deadline:
            time.sleep(0.02)
        pod = _pods(1)[0]
        payload = AdmissionPayload(pod, "CREATE", RequestInfo(), "default")
        r1 = h.pipeline.submit(payload)
        r2 = h.pipeline.submit(payload)
        assert list(r1) == list(r2)
        assert r2.revision == r1.revision
        assert h.pipeline.stats.get("cache_hits", 0) == 1
        # a different manifest is a miss, not a false hit
        other = AdmissionPayload(_pods(2)[1], "CREATE", RequestInfo(),
                                 "default")
        h.pipeline.submit(other)
        assert h.pipeline.stats.get("cache_hits", 0) == 1
    finally:
        h.lifecycle.stop()
        h.pipeline.stop()
        h.batcher.stop()


# ---------------------------------------------------------------------------
# key/hash helpers + persistent XLA cache


def test_resource_content_hash_stability():
    a = {"kind": "Pod", "metadata": {"name": "x", "labels": {"a": "1"}}}
    b = {"metadata": {"labels": {"a": "1"}, "name": "x"}, "kind": "Pod"}
    assert resource_content_hash(a) == resource_content_hash(b)
    assert resource_content_hash({"k": b"bytes"}) is None
    # the scan service threads the snapshot's stored hashes into the
    # verdict keys — the two hash functions must agree byte-for-byte
    from kyverno_tpu.cluster.snapshot import resource_hash

    assert resource_content_hash(a) == resource_hash(a)
    assert request_digest({"t": "a"}, "CREATE", None) != \
        request_digest({"t": "b"}, "CREATE", None)
    assert request_digest({}, "", None) != request_digest({}, "CREATE", None)


def test_xla_compile_cache_dir_populates(tmp_path):
    import jax
    import jax.numpy as jnp

    d = str(tmp_path / "xla")
    assert enable_xla_compile_cache(d) == d
    try:
        assert jax.config.jax_compilation_cache_dir == d

        @jax.jit
        def f(x):
            return x * 2 + 1

        f(jnp.arange(8)).block_until_ready()
    finally:
        # leave the process-global config pristine for other tests
        import kyverno_tpu.tpu.cache as cache_mod

        jax.config.update("jax_compilation_cache_dir", None)
        cache_mod._xla_cache_dir = None
    import os

    assert os.path.isdir(d)


def test_enable_xla_cache_none_disables():
    assert enable_xla_compile_cache("none") is None
    assert enable_xla_compile_cache("") is None
