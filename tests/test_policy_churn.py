"""Policy-churn chaos (slow tier): 64-thread admission load while a
mutator thread adds/updates/deletes policies every 50 ms.

Asserts the lifecycle contract end to end:
- zero dropped requests (no sheds, no deadline expiries, every submit
  answered);
- no batch ever evaluates a mixed-revision policy set — every response
  carries the batch-pinned compiled version, whose snapshot content
  hash must equal the cache's recorded content hash AT that revision;
- every verdict is bit-identical to the scalar oracle evaluated at the
  revision that served it;
- after the churn settles, serving catches up to the final revision.
"""

import threading
import time

import pytest

from kyverno_tpu.api.policy import ClusterPolicy
from kyverno_tpu.cluster import PolicyCache
from kyverno_tpu.engine.engine import Engine as ScalarEngine
from kyverno_tpu.engine.match import RequestInfo
from kyverno_tpu.observability.metrics import global_registry
from kyverno_tpu.resilience import global_faults, tpu_breaker
from kyverno_tpu.serving import BatchConfig
from kyverno_tpu.tpu.engine import (_scalar_rule_verdicts,
                                    build_scan_context)
from kyverno_tpu.tpu.evaluator import NOT_MATCHED
from kyverno_tpu.webhooks import build_handlers
from kyverno_tpu.webhooks.server import AdmissionPayload

pytestmark = pytest.mark.slow

N_THREADS = 64
REQUESTS_PER_THREAD = 4
N_MUTATIONS = 40
MUTATE_EVERY_S = 0.05


@pytest.fixture(autouse=True)
def _clean():
    global_faults.disarm()
    tpu_breaker().reset()
    yield
    global_faults.disarm()
    tpu_breaker().reset()


def _pol(name, priv="false", msg="m"):
    return ClusterPolicy.from_dict({
        "apiVersion": "kyverno.io/v1", "kind": "ClusterPolicy",
        "metadata": {"name": name},
        "spec": {"validationFailureAction": "Enforce", "rules": [{
            "name": "r",
            "match": {"any": [{"resources": {"kinds": ["Pod"]}}]},
            "validate": {"message": msg, "pattern": {"spec": {"containers": [
                {"=(securityContext)": {"=(privileged)": priv}}]}}},
        }]}})


def _pod(i):
    return {"apiVersion": "v1", "kind": "Pod",
            "metadata": {"name": f"p{i}", "namespace": "default"},
            "spec": {"containers": [{
                "name": "c", "image": "nginx",
                "securityContext": {"privileged": i % 2 == 0}}]}}


def _oracle_rows(version, resource):
    """Scalar-oracle verdicts for one resource at EXACTLY the policy
    set the version was compiled from (the revision that served it)."""
    scalar = ScalarEngine()
    out = {}
    for entry in version.engine.cps.rules:
        policy = version.engine.cps.policies[entry.policy_idx]
        pctx = build_scan_context(policy, resource, {}, "CREATE",
                                  RequestInfo())
        verdicts = _scalar_rule_verdicts(scalar, policy, pctx)
        out[(entry.policy_name, entry.rule_name)] = verdicts.get(
            entry.rule_name, NOT_MATCHED)
    return out


def test_policy_churn_under_load_zero_drops_pinned_revisions_exact_verdicts():
    cache = PolicyCache()
    cache.set(_pol("stable"))
    handlers = build_handlers(
        cache, batching=True,
        batch_config=BatchConfig(max_batch_size=16, max_wait_ms=2.0,
                                 deadline_ms=30_000.0))
    # single-mutator revlog: content hash of the cache at EVERY
    # revision, recorded synchronously inside the mutation commit path
    revlog = {}
    revlog_lock = threading.Lock()

    def record(_key, _change, _rev):
        snap = cache.policyset_snapshot()
        with revlog_lock:
            revlog[snap.revision] = snap.content_hash

    snap0 = cache.policyset_snapshot()
    revlog[snap0.revision] = snap0.content_hash
    cache.subscribe(record)
    handlers.lifecycle.start()
    pods = [_pod(i) for i in range(8)]
    responses = []
    res_lock = threading.Lock()
    failures = []
    start_barrier = threading.Barrier(N_THREADS + 1)

    def worker(tid):
        start_barrier.wait()
        local = []
        for i in range(REQUESTS_PER_THREAD):
            pod = pods[(tid + i) % len(pods)]
            try:
                rows = handlers.pipeline.submit(AdmissionPayload(
                    pod, "CREATE", RequestInfo(), "default"))
                local.append((pod, rows))
            except Exception as e:  # noqa: BLE001 — a drop is a failure
                failures.append(f"t{tid}/{i}: {type(e).__name__}: {e}")
                return
            time.sleep(0.02)  # spread requests across the churn window
        with res_lock:
            responses.extend(local)

    def mutator():
        start_barrier.wait()
        for i in range(N_MUTATIONS):
            step = i % 4
            if step == 0:
                cache.set(_pol("churn", priv="true", msg=f"v{i}"))
            elif step == 1:
                cache.set(_pol("extra", msg=f"v{i}"))
            elif step == 2:
                cache.set(_pol("churn", priv="false", msg=f"v{i}"))
            else:
                cache.unset("extra")
            time.sleep(MUTATE_EVERY_S)

    threads = [threading.Thread(target=worker, args=(t,))
               for t in range(N_THREADS)]
    mut = threading.Thread(target=mutator)
    for t in threads:
        t.start()
    mut.start()
    mut.join(timeout=120)
    for t in threads:
        t.join(timeout=120)

    try:
        stats = dict(handlers.pipeline.stats)
        # 1) zero dropped requests
        assert not failures, failures[:5]
        assert len(responses) == N_THREADS * REQUESTS_PER_THREAD
        assert stats["shed"] == 0 and stats["expired"] == 0

        served_revisions = set()
        oracle_cache = {}
        for pod, rows in responses:
            ver = rows.version
            # 2) every batch was pinned to one immutable compiled
            # version whose snapshot matches what the cache actually
            # contained at that revision — no torn/mixed set possible
            assert ver is not None, "response served without a pinned version"
            assert rows.revision == ver.snapshot.revision
            assert revlog.get(rows.revision) == ver.snapshot.content_hash, (
                f"revision {rows.revision} served content "
                f"{ver.snapshot.content_hash}, cache recorded "
                f"{revlog.get(rows.revision)}")
            served_revisions.add(rows.revision)
            # 3) bit-identical to the scalar oracle at THAT revision
            key = (rows.revision, pod["metadata"]["name"])
            if key not in oracle_cache:
                oracle_cache[key] = _oracle_rows(ver, pod)
            got = {pr: code for pr, code in rows}
            assert got == oracle_cache[key], (
                f"verdict drift at revision {rows.revision} "
                f"for {pod['metadata']['name']}")

        # churn really happened and swaps landed while serving
        assert cache.revision >= N_MUTATIONS
        assert handlers.lifecycle.stats["swaps"] >= 1
        assert "kyverno_policyset_swaps_total" in global_registry.exposition()

        # 4) the set settles: serving catches up to the final revision
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            final = handlers.pipeline.submit(AdmissionPayload(
                pods[0], "CREATE", RequestInfo(), "default"))
            if final.version.snapshot.content_hash \
                    == cache.policyset_snapshot().content_hash:
                break
            time.sleep(0.1)
        assert final.version.snapshot.content_hash \
            == cache.policyset_snapshot().content_hash
        assert {pr[0] for pr, _ in final} \
            == {p.name for p in cache.snapshot()[1]}
    finally:
        handlers.lifecycle.stop()
        handlers.pipeline.stop()
        handlers.batcher.stop()
