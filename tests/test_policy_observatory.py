"""Policy observatory: device-side rule analytics, feed-starvation
accounting, SLO burn rates, and their surfaces (/debug/rules,
/debug/utilization, kyverno_rule_* / kyverno_slo_* metrics,
`apply --rule-stats`, `kyverno-tpu top`)."""

import json
import threading
import time

import numpy as np
import pytest

from kyverno_tpu.api.policy import ClusterPolicy
from kyverno_tpu.observability.analytics import (
    RuleIdent, RuleStatsAccumulator, RuleStatsCollector, SloConfig,
    SloTracker, StarvationTracker, class_counts, global_rule_stats,
    policy_spec_hash)
from kyverno_tpu.tpu.engine import TpuEngine


def make_policy(name, rules):
    return ClusterPolicy.from_dict({
        "apiVersion": "kyverno.io/v1", "kind": "ClusterPolicy",
        "metadata": {"name": name},
        "spec": {"validationFailureAction": "Enforce", "rules": rules}})


NAME_RULE = {
    "name": "named",
    "match": {"any": [{"resources": {"kinds": ["Pod"]}}]},
    "validate": {"message": "m",
                 "pattern": {"metadata": {"name": "p?*"}}},
}
# matches a kind the workload never contains -> never fires (the
# runtime half of shadow/dead-rule detection)
SHADOWED_RULE = {
    "name": "shadowed",
    "match": {"any": [{"resources": {"kinds": ["Gateway"]}}]},
    "validate": {"message": "m",
                 "pattern": {"metadata": {"name": "?*"}}},
}
# CEL validate lowers to a host-fallback rule (fallback_reason set):
# exercises the host-row branch of the device-count merge
# size() is outside the lowered CEL subset (tpu/ir.py
# compile_cel_validation), so this rule stays a host rule — the test
# needs one in-set to exercise host-row merging
CEL_RULE = {
    "name": "cel-host",
    "match": {"any": [{"resources": {"kinds": ["Pod"]}}]},
    "validate": {"cel": {"expressions": [
        {"expression": "size(object.metadata.name) >= 1"}]}},
}


def workload(n=7):
    # mixed outcomes, unique names (snapshot upserts key on
    # kind/ns/name): odd names pass the p?* pattern, even ones fail it
    return [{"apiVersion": "v1", "kind": "Pod",
             "metadata": {"name": (f"p{i}" if i % 2 else f"x{i}"),
                          "namespace": "d"},
             "spec": {"containers": [{"name": "c", "image": "nginx"}]}}
            for i in range(n)]


def counts_snapshot():
    return sorted(
        (r["policy"], r["rule"], r["pass"], r["skip"], r["fail"],
         r["not_matched"], r["error"])
        for r in global_rule_stats.rule_rows())


# ---------------------------------------------------------------------------
# primitives


def test_verdict_class_constants_mirror_evaluator():
    """analytics.py must stay importable without jax, so it mirrors the
    evaluator's verdict codes — this is the drift tripwire."""
    from kyverno_tpu.observability import analytics
    from kyverno_tpu.tpu import evaluator

    assert (analytics.PASS, analytics.SKIP, analytics.FAIL,
            analytics.NOT_MATCHED, analytics.ERROR, analytics.HOST) == (
        evaluator.PASS, evaluator.SKIP, evaluator.FAIL,
        evaluator.NOT_MATCHED, evaluator.ERROR, evaluator.HOST)
    assert analytics.NUM_CLASSES == evaluator.NUM_VERDICT_CLASSES


def test_class_counts_matches_naive_loop():
    from kyverno_tpu.observability.analytics import NUM_CLASSES

    rng = np.random.default_rng(7)
    table = rng.integers(0, NUM_CLASSES, size=(11, 37)).astype(np.int32)
    got = class_counts(table)
    for ri in range(11):
        for c in range(NUM_CLASSES):
            assert got[ri, c] == int((table[ri] == c).sum())
    assert class_counts(np.zeros((0, 5), np.int32)).shape == (0, NUM_CLASSES)
    # 1-D column input
    col = np.array([0, 2, 2, 4], np.int32)
    got = class_counts(col)
    assert got[1, 2] == 1 and got[2, 2] == 1 and got[3, 4] == 1


def test_policy_spec_hash_survives_rename_and_tracks_content():
    p1 = make_policy("alpha", [NAME_RULE])
    p2 = make_policy("beta", [NAME_RULE])          # renamed, same spec
    p3 = make_policy("alpha", [NAME_RULE, SHADOWED_RULE])  # content moved
    assert policy_spec_hash(p1) == policy_spec_hash(p2)
    assert policy_spec_hash(p1) != policy_spec_hash(p3)


def test_accumulator_register_and_fired_tracking():
    clock = [100.0]
    acc = RuleStatsAccumulator(clock=lambda: clock[0])
    idents = [RuleIdent("h1", "p", "r1", True),
              RuleIdent("h1", "p", "r2", True)]
    acc.register(idents)
    clock[0] = 160.0
    # r1 fires (2 pass), r2 only not-matched
    acc.ingest_counts(idents, np.array([[2, 0, 0, 1, 0, 0],
                                        [0, 0, 0, 3, 0, 0]]),
                      source="device")
    rep = acc.report(now=160.0)
    assert rep["rules_tracked"] == 2
    assert [r["rule"] for r in rep["top"]] == ["r1"]
    assert rep["top"][0]["by_source"] == {"device": 3}
    never = rep["never_fired"]
    assert [r["rule"] for r in never] == ["r2"]
    assert never[0]["age_s"] == 60.0  # age since registration, not ingest


# ---------------------------------------------------------------------------
# satellite: device vs scalar vs breaker-OPEN vs pipelined parity


class _OpenBreaker:
    name = "test-open"
    state = "open"

    def allow(self):
        return False

    def record_failure(self):
        pass

    def record_success(self):
        pass


def test_rule_stats_parity_across_dispatch_ladder(no_verdict_cache):
    """The acceptance bar: identical per-rule counts for the same
    workload through the device path, the breaker-OPEN scalar
    fallback, and the pipelined scan — with a host-fallback CEL rule in
    the set so host-row merging is exercised too."""
    policies = [make_policy("obs-pol", [NAME_RULE, SHADOWED_RULE]),
                make_policy("cel-pol", [CEL_RULE])]
    res = workload()

    eng = TpuEngine(policies)
    dev_rules, total_rules = eng.coverage()
    assert dev_rules == 2 and total_rules == 3  # CEL rule is host
    r_dev = eng.scan(res)
    device = counts_snapshot()
    # sanity: the device path really counted the workload
    fail_row = [c for c in device if c[1] == "named"][0]
    assert fail_row[2] + fail_row[4] == 7  # 7 pods matched: pass+fail

    global_rule_stats.reset()
    eng_open = TpuEngine(policies, breaker=_OpenBreaker())
    r_fb = eng_open.scan(res)
    assert np.array_equal(r_fb.verdicts, r_dev.verdicts)
    assert counts_snapshot() == device

    global_rule_stats.reset()
    from kyverno_tpu.parallel.sharding import ShardedScanner, make_mesh
    from kyverno_tpu.tpu.pipeline import PipelinedScanner

    pipe = PipelinedScanner(ShardedScanner(policies, mesh=make_mesh()))
    seen = {}
    pipe.scan_chunks([res[:4], res[4:]],
                     on_result=lambda i, r: seen.setdefault(i, r))
    assert counts_snapshot() == device
    piped = np.concatenate([seen[0].verdicts, seen[1].verdicts], axis=1)
    assert np.array_equal(piped, r_dev.verdicts)


def test_rule_stats_exclude_serving_pad_slots(no_verdict_cache):
    """live_n: pad resources ride the shape bucket but must not inflate
    not-matched counts."""
    eng = TpuEngine([make_policy("p", [NAME_RULE])])
    res = workload(5)
    eng.scan(res)
    base = counts_snapshot()
    global_rule_stats.reset()
    eng.scan(res + [{}] * 6, live_n=5)
    assert counts_snapshot() == base


def test_rule_stats_quarantining_scan_counts_bad_columns(no_verdict_cache):
    """A hostile resource that breaks batch encode degrades through the
    quarantining scan; its per-rule verdicts still count exactly once."""
    eng = TpuEngine([make_policy("p", [NAME_RULE])])
    hostile = {"kind": b"bytes-break-encoding", "metadata": {"name": "h"}}
    res = workload(3) + [hostile]
    result = eng.scan(res)
    assert result.verdicts.shape[1] == 4
    rows = global_rule_stats.rule_rows()
    assert len(rows) == 1
    assert rows[0]["evals"] == 4  # 3 good + 1 quarantined column
    assert "quarantine" in rows[0]["by_source"] or \
        "host" in rows[0]["by_source"]


# ---------------------------------------------------------------------------
# satellite: cache-served verdicts count (engine + scan_once replay)


def test_cached_rescan_reports_identical_rule_stats():
    from kyverno_tpu.tpu.cache import global_verdict_cache

    assert global_verdict_cache.enabled
    eng = TpuEngine([make_policy("p", [NAME_RULE, SHADOWED_RULE])])
    res = workload(6)
    eng.scan(res)
    cold = counts_snapshot()
    global_rule_stats.reset()
    eng.scan(res)  # fully cache-served now
    assert counts_snapshot() == cold
    rows = global_rule_stats.rule_rows()
    assert all(set(r["by_source"]) == {"cached"} for r in rows
               if r["evals"])


def test_scan_once_cache_hit_partition_replays_into_accumulator():
    """BackgroundScanService full rescan of an unchanged snapshot is
    ≥90% cache-served — the replayed columns must reproduce the same
    rule stats as the cold scan."""
    from kyverno_tpu.cluster import (BackgroundScanService, ClusterSnapshot,
                                     PolicyCache)

    cache = PolicyCache()
    cache.set(make_policy("p", [NAME_RULE, SHADOWED_RULE]))
    snapshot = ClusterSnapshot()
    for r in workload(8):
        snapshot.upsert(r)
    svc = BackgroundScanService(snapshot, cache, batch_size=4)
    assert svc.scan_once(full=True) == 8
    cold = counts_snapshot()
    assert any(c[2] or c[4] for c in cold)  # something fired
    global_rule_stats.reset()
    assert svc.scan_once(full=True) == 8
    assert svc.stats["verdict_cache_hits"] >= 7
    assert counts_snapshot() == cold


# ---------------------------------------------------------------------------
# acceptance: shadowed rule reported never-fired after a full scan


def test_debug_rules_reports_shadowed_rule_never_fired():
    from kyverno_tpu.cluster import (BackgroundScanService, ClusterSnapshot,
                                     PolicyCache)
    from kyverno_tpu.webhooks.server import handle_debug_path

    cache = PolicyCache()
    cache.set(make_policy("obs", [NAME_RULE, SHADOWED_RULE]))
    snapshot = ClusterSnapshot()
    for r in workload(6):
        snapshot.upsert(r)
    svc = BackgroundScanService(snapshot, cache)
    assert svc.scan_once(full=True) == 6

    code, body, ctype = handle_debug_path("/debug/rules?top=5")
    assert code == 200 and ctype == "application/json"
    doc = json.loads(body)
    hot = {(r["policy"], r["rule"]) for r in doc["top"]}
    never = {(r["policy"], r["rule"]) for r in doc["never_fired"]}
    assert ("obs", "named") in hot
    assert ("obs", "shadowed") in never
    assert all(r["age_s"] >= 0 for r in doc["never_fired"])
    pol = [p for p in doc["policies"] if p["policy"] == "obs"][0]
    assert pol["device_coverage"] == 1.0
    # the shadowed rule plus whatever autogen expansion added (those
    # siblings match kinds absent from this workload too)
    assert pol["never_fired"] >= 1
    # bad query param is a 400, not a traceback
    assert handle_debug_path("/debug/rules?top=x")[0] == 400


def test_debug_utilization_surface():
    from kyverno_tpu.webhooks.server import handle_debug_path

    # drive one scan so starvation/utilization have samples
    eng = TpuEngine([make_policy("p", [NAME_RULE])])
    eng.scan(workload(4))
    code, body, _ = handle_debug_path("/debug/utilization")
    assert code == 200
    doc = json.loads(body)
    ratio = doc["feed_starvation"]["ratio"]
    assert 0.0 <= ratio <= 1.0
    assert "encode_wait" in doc["feed_starvation"]["seconds_total"]
    assert "slo" in doc and "pipeline" in doc
    assert "verdict_hit_rate" in doc["perf_caches"]


# ---------------------------------------------------------------------------
# starvation tracker + pipeline gauge liveness (satellite 1)


def test_starvation_tracker_windows_and_bounds():
    clock = [0.0]
    tr = StarvationTracker(window_s=10.0, clock=lambda: clock[0])
    assert tr.ratio() == 0.0
    tr.record(busy_s=1.0, starved_s=3.0)
    assert tr.ratio() == 0.75
    tr.record(busy_s=1.0, starved_s=0.0)
    assert tr.ratio() == 0.6
    clock[0] = 60.0  # both events age out of the window
    assert tr.ratio() == 0.0
    assert tr.state()["seconds_total"]["device_busy"] == 2.0


def test_pipeline_overlap_gauge_updates_per_chunk(no_verdict_cache):
    """Satellite: mid-scan scrapes must see live overlap values — the
    gauge is set from drain(), once per chunk, not once at scan end."""
    from kyverno_tpu.observability.metrics import global_registry
    from kyverno_tpu.parallel.sharding import ShardedScanner, make_mesh
    from kyverno_tpu.tpu.pipeline import PipelinedScanner

    pipe = PipelinedScanner(
        ShardedScanner([make_policy("p", [NAME_RULE])], mesh=make_mesh()))
    updates = []
    orig_set = global_registry.pipeline_overlap.set

    def spy(value, labels=None):
        updates.append(value)
        orig_set(value, labels)

    global_registry.pipeline_overlap.set = spy
    try:
        res = workload(9)
        stats = pipe.scan_chunks([res[:3], res[3:6], res[6:]])
    finally:
        global_registry.pipeline_overlap.set = orig_set
    # one live update per chunk + the final one from the finally block
    assert len(updates) >= 4
    assert len(stats["timeline"]) == 3
    assert {t["chunk"] for t in stats["timeline"]} == {0, 1, 2}
    assert all(t["path"] == "device" for t in stats["timeline"])
    assert 0.0 <= stats["overlap_ratio"]
    starv = global_registry.feed_starvation.value()
    assert 0.0 <= starv <= 1.0


# ---------------------------------------------------------------------------
# SLO layer


def test_slo_burn_rates_multi_window():
    clock = [1000.0]
    slo = SloTracker(
        config=SloConfig(admission_p99_target_ms=10.0,
                         admission_error_budget=0.1,
                         windows={"short": 60.0, "long": 600.0}),
        metrics=object(),  # no gauge surface: state() is the API here
        clock=lambda: clock[0])
    # 8 fast + 2 slow in the short window -> 20% violations / 10%
    # budget = burn 2.0
    for _ in range(8):
        slo.record_admission(0.001)
    for _ in range(2):
        slo.record_admission(0.5)
    st = slo.state(now=clock[0])
    assert st["admission"]["windows"]["short"]["burn_rate"] == 2.0
    assert st["admission"]["windows"]["long"]["burn_rate"] == 2.0
    assert "admission_latency" in st["breached"]
    # the short window forgets, the long window remembers
    clock[0] += 120.0
    for _ in range(40):
        slo.record_admission(0.001)
    st = slo.state(now=clock[0])
    assert st["admission"]["windows"]["short"]["burn_rate"] == 0.0
    assert st["admission"]["windows"]["long"]["burn_rate"] == \
        pytest.approx((2 / 50) / 0.1)
    # scan freshness burns as the clock runs without scans
    slo.record_scan(coverage=0.95)
    st = slo.state(now=clock[0] + 30.0)
    assert st["scan_freshness"]["seconds_since_scan"] == 30.0
    assert st["scan_freshness"]["burn_rate"] < 1.0
    st = slo.state(now=clock[0] + 900.0)
    assert "scan_freshness" in st["breached"]
    # coverage floor
    slo.set_device_coverage(0.5)
    st = slo.state(now=clock[0] + 30.0)
    assert "device_coverage" in st["breached"]


def test_slo_gauges_on_metrics_and_readyz_state():
    from kyverno_tpu.cluster import PolicyCache
    from kyverno_tpu.observability.analytics import global_slo
    from kyverno_tpu.observability.metrics import global_registry
    from kyverno_tpu.webhooks import build_handlers

    global_slo.record_admission(0.002)
    global_slo.record_scan(coverage=1.0)
    text = global_registry.exposition()
    assert "kyverno_slo_admission_burn_rate" in text
    assert "kyverno_slo_scan_freshness_seconds" in text
    assert "kyverno_slo_device_coverage_ratio" in text
    cache = PolicyCache()
    cache.set(make_policy("p", [NAME_RULE]))
    handlers = build_handlers(cache)
    ok, detail = handlers.ready()
    assert "slo" in detail
    assert detail["slo"]["device_coverage"]["ratio"] == 1.0
    assert "windows" in detail["slo"]["admission"]


def test_admission_pipeline_feeds_slo_window():
    from kyverno_tpu.observability.analytics import global_slo
    from kyverno_tpu.serving import AdmissionPipeline, BatchConfig

    pipe = AdmissionPipeline(
        lambda padded: ["ok" for p in padded if p is not None],
        config=BatchConfig(max_batch_size=4, max_wait_ms=1.0))
    try:
        for _ in range(5):
            assert pipe.submit("x") == "ok"
    finally:
        pipe.stop()
    st = global_slo.state()
    windows = st["admission"]["windows"]
    assert any(w["requests"] >= 5 for w in windows.values())


# ---------------------------------------------------------------------------
# cardinality-bounded exposition (satellite 4 lives with the validator
# test too; this is the dedicated guard)


def _parse_policy_labels(text, family):
    import re

    out = []
    for line in text.splitlines():
        m = re.match(rf'{family}\{{policy="([^"]+)"\}} ([0-9.eE+-]+)$', line)
        if m:
            out.append((m.group(1), float(m.group(2))))
    return out


def test_rule_metric_cardinality_collapses_into_overflow():
    acc = RuleStatsAccumulator(clock=lambda: 0.0)
    k = 5
    n_policies = k + 7
    total_evals = 0
    for i in range(n_policies):
        ident = RuleIdent(f"hash{i}", f"pol-{i:02d}", "r", True)
        evals = 10 * (i + 1)
        total_evals += evals
        acc.ingest_counts([ident], np.array([[evals, 0, 0, 0, 0, 0]]))
    coll = RuleStatsCollector(accumulator=acc, top_k=k)
    text = "\n".join(coll.collect())
    series = _parse_policy_labels(text, "kyverno_rule_evals_total")
    labels = {s[0] for s in series}
    # bounded: exactly top-K named policies + ONE overflow bucket
    assert len(series) == k + 1
    assert "_overflow" in labels
    # top-K by eval volume keep their own label
    expect_named = {f"pol-{i:02d}" for i in range(n_policies - k, n_policies)}
    assert labels - {"_overflow"} == expect_named
    # nothing lost: the overflow bucket carries the remainder
    assert sum(v for _, v in series) == total_evals
    # per-family coverage: every family stays bounded
    for fam in ("kyverno_rule_fired_total", "kyverno_rule_fail_total",
                "kyverno_rule_never_fired", "kyverno_policy_device_coverage"):
        assert len(_parse_policy_labels(text, fam)) == k + 1


def test_rule_metrics_on_global_registry_exposition():
    from kyverno_tpu.observability.metrics import global_registry

    eng = TpuEngine([make_policy("expo", [NAME_RULE, SHADOWED_RULE])])
    eng.scan(workload(4))
    text = global_registry.exposition()
    assert 'kyverno_rule_evals_total{policy="expo"}' in text
    assert 'kyverno_rule_never_fired{policy="expo"} 1.0' in text
    assert 'kyverno_policy_device_coverage{policy="expo"} 1.0' in text


# ---------------------------------------------------------------------------
# admission paths: device vs scalar toggle vs submit-cache parity


def _mk_handlers(**kw):
    from kyverno_tpu.cluster import PolicyCache
    from kyverno_tpu.webhooks import build_handlers

    cache = PolicyCache()
    cache.set(make_policy("adm", [NAME_RULE, SHADOWED_RULE]))
    return build_handlers(cache, **kw)


def _payloads(n=5):
    from kyverno_tpu.engine.match import RequestInfo
    from kyverno_tpu.webhooks.server import AdmissionPayload

    return [AdmissionPayload(r, "CREATE", RequestInfo(), "d")
            for r in workload(n)]


def test_admission_device_vs_scalar_toggle_rule_stats_parity(
        no_verdict_cache):
    from kyverno_tpu.config import Toggles

    handlers = _mk_handlers()
    pads = _payloads() + [None] * 3
    handlers._evaluate_padded(list(pads))
    device = counts_snapshot()
    assert any(c[2] or c[4] for c in device)

    global_rule_stats.reset()
    handlers_scalar = _mk_handlers(toggles=Toggles(engine="scalar"))
    handlers_scalar._evaluate_padded(list(pads))
    assert counts_snapshot() == device


def test_submit_time_cache_hit_replays_column():
    """A repeat admission served at submit() (before the queue) still
    lands in the accumulator, tagged as cached."""
    handlers = _mk_handlers()
    payload = _payloads(1)[0]
    handlers._evaluate_padded([payload])  # populates the verdict cache
    base = counts_snapshot()
    global_rule_stats.reset()
    rows = handlers._cached_verdict_rows(payload)
    assert rows is not None
    assert counts_snapshot() == base
    tracked = global_rule_stats.rule_rows()
    assert all(set(r["by_source"]) == {"cached"} for r in tracked
               if r["evals"])


# ---------------------------------------------------------------------------
# CLI: apply --rule-stats and kyverno-tpu top


def test_apply_rule_stats_flag(tmp_path, capsys):
    import yaml

    from kyverno_tpu.cli.__main__ import main

    pol = {"apiVersion": "kyverno.io/v1", "kind": "ClusterPolicy",
           "metadata": {"name": "cli-pol"},
           "spec": {"rules": [NAME_RULE, SHADOWED_RULE]}}
    pf = tmp_path / "pol.yaml"
    pf.write_text(yaml.safe_dump(pol))
    rf = tmp_path / "res.yaml"
    rf.write_text(yaml.safe_dump_all(workload(3)))
    rc = main(["apply", str(pf), "-r", str(rf), "--rule-stats"])
    assert rc in (0, 1)
    err = capsys.readouterr().err
    assert "per-rule analytics" in err
    assert "cli-pol/named" in err
    assert "never fired" in err and "cli-pol/shadowed" in err


def test_top_command_renders_against_live_serve(capsys):
    from kyverno_tpu.cli.serve import ControlPlane
    from kyverno_tpu.cli.__main__ import main

    cp = ControlPlane([make_policy("top-pol", [NAME_RULE, SHADOWED_RULE])],
                      port=0, metrics_port=0)
    cp.start(scan_interval=3600.0)
    try:
        for r in workload(4):
            _post_json(cp, "/snapshot/upsert", r)
        _post_json(cp, "/scan", {"full": True})
        port = cp.metrics_server.server_address[1]
        rc = main(["top", "--port", str(port), "--iterations", "1",
                   "--no-clear"])
        assert rc == 0
        out = capsys.readouterr().out
        assert "kyverno-tpu top" in out
        assert "top-pol/named" in out
        # autogen expansion adds sibling rules; the shadowed one must be
        # listed among the never-fired set either way
        assert "never fired (" in out and "top-pol/shadowed" in out
        assert "feed starvation" in out
    finally:
        cp.stop()


def _post_json(cp, path, doc):
    import http.client

    port = cp.metrics_server.server_address[1]
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=30)
    try:
        conn.request("POST", path, json.dumps(doc),
                     {"Content-Type": "application/json"})
        resp = conn.getresponse()
        body = resp.read()
        assert resp.status == 200, body
        return json.loads(body)
    finally:
        conn.close()


# ---------------------------------------------------------------------------
# device-count merge corner: stale stashes must never leak


def test_pending_counts_cleared_on_dispatch_failure(no_verdict_cache):
    """A dispatch that fails AFTER the device returned (shape
    validation) must not leave its counts behind for the all-HOST
    fallback assemble — counts then come from the final table."""
    from kyverno_tpu.resilience.faults import global_faults

    eng = TpuEngine([make_policy("p", [NAME_RULE])])
    res = workload(4)
    expected = eng.scan(res)
    base = counts_snapshot()
    global_rule_stats.reset()
    global_faults.arm("tpu.dispatch", mode="raise", p=1.0)
    try:
        r = eng.scan(res)
    finally:
        global_faults.disarm()
        eng.breaker.reset()
    assert np.array_equal(r.verdicts, expected.verdicts)
    assert counts_snapshot() == base
    rows = global_rule_stats.rule_rows()
    assert all(set(r["by_source"]) == {"host"} for r in rows if r["evals"])
