"""Policy object validation."""

from kyverno_tpu.api.policy import ClusterPolicy
from kyverno_tpu.policies import load_pss_policies
from kyverno_tpu.policy.validation import validate_policy


def make(spec_rules, background=True):
    return ClusterPolicy.from_dict({
        "apiVersion": "kyverno.io/v1", "kind": "ClusterPolicy",
        "metadata": {"name": "p"},
        "spec": {"background": background, "rules": spec_rules},
    })


GOOD_RULE = {
    "name": "r1",
    "match": {"any": [{"resources": {"kinds": ["Pod"]}}]},
    "validate": {"pattern": {"spec": {"x": "y"}}},
}


def test_valid_policy_passes():
    errs, warns = validate_policy(make([GOOD_RULE]))
    assert errs == [] and warns == []


def test_bundled_pss_policies_validate():
    for p in load_pss_policies():
        errs, warns = validate_policy(p)
        assert errs == [], (p.name, errs)
        assert warns == [], (p.name, warns)


def test_duplicate_and_multi_type_rules():
    bad = dict(GOOD_RULE)
    bad2 = dict(GOOD_RULE)
    bad2["mutate"] = {"patchStrategicMerge": {}}
    errs, _ = validate_policy(make([bad, bad2]))
    assert any("duplicate rule name" in e for e in errs)
    assert any("exactly one of" in e for e in errs)


def test_empty_match_and_missing_body():
    errs, _ = validate_policy(make([{
        "name": "r", "match": {}, "validate": {}}]))
    assert any("match block cannot be empty" in e for e in errs)
    assert any("requires one of" in e for e in errs)


def test_background_forbidden_variables():
    rule = {
        "name": "r",
        "match": {"any": [{"resources": {"kinds": ["Pod"]}}]},
        "validate": {"deny": {"conditions": {"all": [{
            "key": "{{ request.userInfo.username }}",
            "operator": "Equals", "value": "x"}]}}},
    }
    errs, _ = validate_policy(make([rule], background=True))
    assert any("background policies cannot reference" in e for e in errs)
    errs, _ = validate_policy(make([rule], background=False))
    assert not any("background" in e for e in errs)


def test_unknown_variable_warns():
    rule = {
        "name": "r",
        "match": {"any": [{"resources": {"kinds": ["Pod"]}}]},
        "validate": {"pattern": {"spec": {"x": "{{ mystery.var }}"}}},
    }
    _, warns = validate_policy(make([rule]))
    assert any("mystery.var" in w for w in warns)
    # context entries whitelist their name
    rule2 = dict(rule)
    rule2["context"] = [{"name": "mystery", "variable": {"value": 1}}]
    rule2["validate"] = {"pattern": {"spec": {"x": "{{ mystery.var }}"}}}
    _, warns = validate_policy(make([rule2]))
    assert warns == []


def test_plus_anchor_rejected_in_validate():
    rule = {
        "name": "r",
        "match": {"any": [{"resources": {"kinds": ["Pod"]}}]},
        "validate": {"pattern": {"spec": {"+(x)": "y"}}},
    }
    errs, _ = validate_policy(make([rule]))
    assert any("+()" in e for e in errs)
