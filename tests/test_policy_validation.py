"""Policy object validation."""

from kyverno_tpu.api.policy import ClusterPolicy
from kyverno_tpu.policies import load_pss_policies
from kyverno_tpu.policy.validation import validate_policy


def make(spec_rules, background=True):
    return ClusterPolicy.from_dict({
        "apiVersion": "kyverno.io/v1", "kind": "ClusterPolicy",
        "metadata": {"name": "p"},
        "spec": {"background": background, "rules": spec_rules},
    })


GOOD_RULE = {
    "name": "r1",
    "match": {"any": [{"resources": {"kinds": ["Pod"]}}]},
    "validate": {"pattern": {"spec": {"x": "y"}}},
}


def test_valid_policy_passes():
    errs, warns = validate_policy(make([GOOD_RULE]))
    assert errs == [] and warns == []


def test_bundled_pss_policies_validate():
    for p in load_pss_policies():
        errs, warns = validate_policy(p)
        assert errs == [], (p.name, errs)
        assert warns == [], (p.name, warns)


def test_duplicate_and_multi_type_rules():
    bad = dict(GOOD_RULE)
    bad2 = dict(GOOD_RULE)
    bad2["mutate"] = {"patchStrategicMerge": {}}
    errs, _ = validate_policy(make([bad, bad2]))
    assert any("duplicate rule name" in e for e in errs)
    assert any("exactly one of" in e for e in errs)


def test_empty_match_and_missing_body():
    errs, _ = validate_policy(make([{
        "name": "r", "match": {}, "validate": {}}]))
    assert any("match block cannot be empty" in e for e in errs)
    assert any("requires one of" in e for e in errs)


def test_background_forbidden_variables():
    rule = {
        "name": "r",
        "match": {"any": [{"resources": {"kinds": ["Pod"]}}]},
        "validate": {"deny": {"conditions": {"all": [{
            "key": "{{ request.userInfo.username }}",
            "operator": "Equals", "value": "x"}]}}},
    }
    errs, _ = validate_policy(make([rule], background=True))
    assert any("background policies cannot reference" in e for e in errs)
    errs, _ = validate_policy(make([rule], background=False))
    assert not any("background" in e for e in errs)


def test_unknown_variable_warns():
    rule = {
        "name": "r",
        "match": {"any": [{"resources": {"kinds": ["Pod"]}}]},
        "validate": {"pattern": {"spec": {"x": "{{ mystery.var }}"}}},
    }
    _, warns = validate_policy(make([rule]))
    assert any("mystery.var" in w for w in warns)
    # context entries whitelist their name
    rule2 = dict(rule)
    rule2["context"] = [{"name": "mystery", "variable": {"value": 1}}]
    rule2["validate"] = {"pattern": {"spec": {"x": "{{ mystery.var }}"}}}
    _, warns = validate_policy(make([rule2]))
    assert warns == []


def test_plus_anchor_rejected_in_validate():
    rule = {
        "name": "r",
        "match": {"any": [{"resources": {"kinds": ["Pod"]}}]},
        "validate": {"pattern": {"spec": {"+(x)": "y"}}},
    }
    errs, _ = validate_policy(make([rule]))
    assert any("+()" in e for e in errs)


def _pol(rule):
    from kyverno_tpu.api.policy import ClusterPolicy
    return ClusterPolicy.from_dict({
        "apiVersion": "kyverno.io/v1", "kind": "ClusterPolicy",
        "metadata": {"name": "p"},
        "spec": {"rules": [rule]},
    })


def test_condition_operator_validation():
    from kyverno_tpu.policy.validation import validate_policy

    rule = {"name": "r",
            "match": {"any": [{"resources": {"kinds": ["Pod"]}}]},
            "preconditions": {"all": [{"key": "x", "operator": "Equalz",
                                       "value": "y"}]},
            "validate": {"pattern": {"metadata": {}}}}
    errs, _ = validate_policy(_pol(rule))
    assert any("invalid condition operator 'Equalz'" in e for e in errs)
    # request.operation values constrained (validate.go:1139)
    rule["preconditions"] = {"all": [{
        "key": "{{request.operation}}", "operator": "Equals",
        "value": "PATCH"}]}
    errs, _ = validate_policy(_pol(rule))
    assert any("unknown value 'PATCH'" in e for e in errs)


def test_context_entry_validation():
    from kyverno_tpu.policy.validation import validate_policy

    rule = {"name": "r",
            "match": {"any": [{"resources": {"kinds": ["Pod"]}}]},
            "context": [
                {"name": "images", "configMap": {"name": "x", "namespace": "y"}},
                {"name": "two", "configMap": {"name": "x"}, "variable": {"value": 1}},
                {"name": "none"},
                {"name": "badcall", "apiCall": {}},
            ],
            "validate": {"pattern": {"metadata": {}}}}
    errs, _ = validate_policy(_pol(rule))
    assert any("shadows a reserved variable" in e for e in errs)
    assert sum("exactly one of" in e for e in errs) == 2
    assert any("urlPath or service.url" in e for e in errs)


def test_json_patch_and_forbidden_variables():
    from kyverno_tpu.policy.validation import validate_policy

    rule = {"name": "r",
            "match": {"any": [{"resources": {"kinds": ["Pod"],
                                             "names": ["{{request.object.x}}"]}}]},
            "mutate": {"patchesJson6902":
                       '[{"op": "patchify", "path": "nope"}]'}}
    errs, _ = validate_policy(_pol(rule))
    assert any("invalid op" in e for e in errs)
    assert any("path must start with '/'" in e for e in errs)
    assert any("variables are not allowed in the match section" in e for e in errs)


def test_generate_validation_and_auth_seam():
    from kyverno_tpu.policy.validation import validate_policy

    rule = {"name": "r",
            "match": {"any": [{"resources": {"kinds": ["Namespace"]}}]},
            "generate": {"kind": "NetworkPolicy", "name": "np",
                         "namespace": "{{request.object.metadata.name}}",
                         "data": {"spec": {}}}}
    errs, _ = validate_policy(_pol(rule))
    assert errs == []
    # both data and clone is invalid
    bad = dict(rule)
    bad["generate"] = {**rule["generate"], "clone": {"name": "x"}}
    errs, _ = validate_policy(_pol(bad))
    assert any("exactly one of" in e for e in errs)
    # auth seam: denied permission -> CanIGenerate error
    errs, _ = validate_policy(_pol(rule),
                              auth_checker=lambda verb, kind, ns: False)
    assert any("CanIGenerate" in e for e in errs)
    errs, _ = validate_policy(_pol(rule),
                              auth_checker=lambda verb, kind, ns: True)
    assert errs == []
