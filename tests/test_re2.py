"""RE2-subset engine (cel/re2.py): differential parity against Python
re on the compatible subset, RE2-specific semantics where the two
diverge, rejection of non-RE2 constructs, and linear-time behavior on
patterns that detonate a backtracking engine."""

import re as pyre
import time

import pytest

from kyverno_tpu.cel.re2 import Re2Error, search

# (pattern, subjects) — RE2-compatible, same semantics as Python re
DIFFERENTIAL = [
    (r"abc", ["abc", "xabcx", "ab", ""]),
    (r"^abc$", ["abc", "xabc", "abcx"]),
    (r"a.c", ["abc", "a\nc", "ac", "axc"]),
    (r"(?s)a.c", ["a\nc", "abc"]),
    (r"a*", ["", "aaa", "b"]),
    (r"a+b", ["b", "ab", "aaab", "aa"]),
    (r"colou?r", ["color", "colour", "colr"]),
    (r"a{3}", ["aa", "aaa", "aaaa"]),
    (r"a{2,}", ["a", "aa", "aaaa"]),
    (r"a{2,4}$", ["a", "aa", "aaaa", "aaaaa"]),
    (r"[abc]+", ["cab", "d", ""]),
    (r"[^abc]+", ["xyz", "abc", "axb"]),
    (r"[a-fA-F0-9]{2}", ["3F", "g1", "a0"]),
    (r"[-a]b", ["-b", "ab", "cb"]),
    (r"(ab|cd)+ef", ["abef", "cdabef", "adef"]),
    (r"^(GET|POST|PUT)\s", ["GET /x", "POST y", "PATCH z"]),
    (r"\d+\.\d+", ["3.14", "a.b", "10.2.3"]),
    (r"\w+@\w+\.\w+", ["a@b.co", "a@b", "x y@z.io w"]),
    (r"\s", [" ", "\t", "a"]),
    (r"\bfoo\b", ["foo", "foobar", "a foo b", "xfoo"]),
    (r"\Bar", ["bar", "ar", "car"]),
    (r"(?i)hello", ["HELLO", "HeLLo", "help"]),
    (r"(?i:ab)c", ["ABc", "ABC", "abc"]),
    (r"(?m)^b$", ["a\nb\nc", "ab"]),
    (r"^(\d{1,3}\.){3}\d{1,3}$", ["10.0.0.1", "255.255.255.255", "1.2.3",
                                  "1.2.3.4.5", "a.b.c.d"]),
    (r"nginx:[0-9.]+", ["nginx:1.25", "nginx:latest"]),
    (r"^[a-z0-9]([-a-z0-9]*[a-z0-9])?$", ["pod-1", "-pod", "a", "Pod"]),
    (r"\x41+", ["AAA", "B"]),
    (r"(a+)+$", ["aaab", "aaa"]),   # catastrophic for backtrackers
    (r"(a*)*b", ["aaab", "c"]),
    (r"x|", ["x", "y", ""]),
    (r"()", ["", "a"]),
    (r"a\0b", ["a\0b".replace(r"\0", "\0"), "ab"]),
    (r"\Az", ["z", "az"]),
]


def test_differential_vs_python_re():
    for pat, subjects in DIFFERENTIAL:
        ref = pyre.compile(pat)
        for s in subjects:
            assert search(pat, s) == (ref.search(s) is not None), (pat, s)


def test_re2_divergences_from_python_re():
    # $ is end-of-TEXT in RE2 (Python re matches before a trailing \n)
    assert search(r"abc$", "abc\n") is False
    assert pyre.search(r"abc$", "abc\n") is not None
    # \d, \w, \s are ASCII in RE2 (Python re is Unicode by default)
    assert search(r"^\d$", "٣") is False       # Arabic-Indic digit
    assert pyre.search(r"^\d$", "٣") is not None
    assert search(r"^\w$", "é") is False
    assert pyre.search(r"^\w$", "é") is not None
    # \x{...} is RE2 syntax Python re lacks
    assert search(r"\x{1F600}", "\U0001F600") is True
    assert search(r"\x{1F600}", "x") is False
    # POSIX classes are RE2 syntax Python re lacks
    assert search(r"[[:alpha:]]+[[:digit:]]", "ab3") is True
    assert search(r"[[:alpha:]]+[[:digit:]]", "3a") is False
    assert search(r"[[:^digit:]]", "a") is True
    assert search(r"[[:^digit:]]", "7") is False


def test_rejects_non_re2_constructs():
    for pat in (r"(a)\1", r"a(?=b)", r"a(?!b)", r"(?<=a)b", r"(?<!a)b",
                r"(?P=name)", r"a*+", r"a**", r"a{2}{3}", r"\p{Greek}",
                r"a{1001}", r"(?(1)a|b)"):
        with pytest.raises(Re2Error):
            search(pat, "x")


def test_linear_time_on_catastrophic_patterns():
    subject = "a" * 2000 + "b" * 5
    for pat in (r"(a+)+c$", r"(a*)*c", r"(a|aa)+c", r"([a-z]+)*c$"):
        t0 = time.perf_counter()
        assert search(pat, subject) is False
        assert time.perf_counter() - t0 < 2.0, pat


def test_named_groups_and_nesting():
    assert search(r"(?P<y>\d{4})-(?P<m>\d{2})", "2026-07-30")
    assert search(r"((a|b)(c|d))+e", "acbde")
    assert not search(r"((a|b)(c|d))+e", "abe")


def test_matches_via_cel():
    from kyverno_tpu.cel import CelError, eval_expression

    assert eval_expression('"10.0.0.1".matches("^(\\\\d{1,3}\\\\.){3}\\\\d{1,3}$")', {}) is True
    assert eval_expression('"a-b".matches("^[a-z]([-a-z]*[a-z])?$")', {}) is True
    with pytest.raises(CelError):
        eval_expression('"aa".matches("(a)\\\\1")', {})
    # catastrophic pattern: returns (quickly) instead of hanging
    t0 = time.perf_counter()
    assert eval_expression(f'"{"a" * 500}b".matches("(a+)+c$")', {}) is False
    assert time.perf_counter() - t0 < 2.0
