"""Replay of the reference's declarative CLI test corpus.

The reference ships kyverno-test.yaml fixtures (test/cli/test/*: a
Test doc naming policies, resources and expected per-rule results —
SURVEY §4 'CLI declarative tests'). This harness replays every fixture
through OUR `kyverno test` runner (kyverno_tpu/cli/test.py — the same
code path users run) and diffs the verdicts. Fixtures are read from
/root/reference at test time (test data, not code); directories
exercising subsystems we intentionally stub (live OCI registries) are
skipped explicitly so any NEW mismatch fails the suite.

NOTE on want=fail rows: the reference's own harness auto-passes every
row whose expected result is `fail` regardless of the actual verdict
(commands/test/output.go:196 `success := ok || (!ok && test.Result ==
StatusFail)`), so such rows are unverified upstream. We still compare
them strictly, and record the few whose fixtures contradict the
reference *engine*'s actual semantics as KNOWN_DIVERGENCES.
"""

from pathlib import Path

import pytest

from kyverno_tpu.cli.test import TestCase, _run_case

CORPUS = Path("/root/reference/test/cli/test")

# directories whose fixtures need subsystems out of scope for offline
# replay. Each entry is a (dirname, reason) pair — additions require
# justification.
SKIP_DIRS = {
    "registry": "needs a live OCI registry (imageRegistry context data "
                "is fetched from ghcr.io; the reference runs this dir "
                "only in its registry-enabled CI lane)",
    "container_reorder": "verifyImages with cosign signatures fetched "
                         "from live ghcr.io (the reference CLI always "
                         "builds a real registry client, "
                         "policy_processor.go:71-74)",
    "images/signatures": "verifyImages static-key verification against "
                         "live ghcr.io signature payloads",
    "images/verify-signature": "verifyImages static-key verification "
                               "against live ghcr.io signature payloads",
}

# individual expected-result rows known to diverge, keyed
# (dirname, policy, rule, resource): reason.
KNOWN_DIVERGENCES = {
    ("simple", "restrict-pod-counts", "restrict-pod-count",
     "test/test-require-image-tag-fail"):
        "values pin request.operation to \"\" so the reference engine "
        "skips on the Equals-CREATE precondition; the fixture's `fail` "
        "expectation is never enforced upstream (output.go:196 "
        "auto-passes want=fail rows)",
}


def _case_dirs():
    if not CORPUS.exists():
        return []
    # fixtures nest (images/digest, manifests/verify-signature, ...);
    # discover kyverno-test.yaml recursively like the reference harness
    return sorted(p.parent for p in CORPUS.rglob("kyverno-test.yaml"))


def _evaluate_dir(d: Path):
    """Returns (matches, mismatches, known) row-key lists for one
    fixture, replayed through the real CLI test runner."""
    case = TestCase(str(d / "kyverno-test.yaml"))
    matches, mismatches, known = [], [], []
    dir_key = str(d.relative_to(CORPUS))
    for exp, res_name, actual, ok in _run_case(case):
        row_key = (dir_key, exp.get("policy", ""), exp.get("rule", ""),
                   res_name or "")
        want = (exp.get("result") or exp.get("status") or "").lower()
        if ok:
            matches.append(row_key)
        elif row_key in KNOWN_DIVERGENCES:
            known.append(row_key)
        else:
            mismatches.append((*row_key, f"want {want}, got {actual}"))
    return matches, mismatches, known


@pytest.mark.skipif(not CORPUS.exists(), reason="reference corpus unavailable")
def test_reference_cli_corpus_replay():
    total_matches, total_mismatches, total_known = [], [], []
    broken_dirs = []
    replayed = 0
    for d in _case_dirs():
        dir_key = str(d.relative_to(CORPUS))
        if dir_key in SKIP_DIRS or dir_key.split("/")[0] in SKIP_DIRS:
            continue
        try:
            m, mm, kn = _evaluate_dir(d)
        except Exception as e:
            broken_dirs.append((dir_key, f"{type(e).__name__}: {e}"))
            continue
        replayed += 1
        total_matches += m
        total_mismatches += mm
        total_known += kn
    summary = (f"corpus: {replayed} dirs replayed, "
               f"{len(total_matches)} matched, "
               f"{len(total_mismatches)} mismatched, "
               f"{len(total_known)} known divergences, "
               f"{len(broken_dirs)} dirs unloadable")
    print("\n" + summary)
    for row in total_mismatches[:40]:
        print("MISMATCH:", row)
    for row in broken_dirs[:10]:
        print("BROKEN:", row)
    # breadth floor: the corpus must contribute a substantial number of
    # matched golden verdicts, no unexplained mismatches, and no
    # unloadable directories
    assert replayed >= 48, summary
    assert len(total_matches) >= 150, summary
    assert not broken_dirs, summary
    assert not total_mismatches, summary
