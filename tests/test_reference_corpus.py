"""Replay of the reference's declarative CLI test corpus.

The reference ships kyverno-test.yaml fixtures (test/cli/test/*: a
Test doc naming policies, resources and expected per-rule results —
SURVEY §4 'CLI declarative tests'). This harness replays every fixture
through our scalar engine and diffs the verdicts. Fixtures are read
from /root/reference at test time (test data, not code); directories
exercising subsystems we intentionally stub (cluster-backed context,
git, registries needing network) are skipped explicitly so any NEW
mismatch fails the suite."""

import os
from pathlib import Path

import pytest
import yaml

from kyverno_tpu.api.policy import ClusterPolicy, is_policy_document
from kyverno_tpu.engine.context import Context
from kyverno_tpu.engine.contextloaders import DataSources
from kyverno_tpu.engine.engine import Engine
from kyverno_tpu.engine.policycontext import PolicyContext
from kyverno_tpu.policy.autogen import expand_policy

CORPUS = Path("/root/reference/test/cli/test")

# directories whose fixtures need subsystems out of scope for offline
# replay (cluster API data, image registries, git). Each entry is a
# (dirname, reason) pair — additions require justification.
SKIP_DIRS = {
    "registry": "needs a live OCI registry (imageRegistry context)",
    "custom-functions": "x509_decode over real certs",
    "exec-subresource-with-user-info": "subresource admission shapes",
}

# individual expected-result rows known to diverge, keyed
# (dirname, policy, rule, resource): reason. Empty = full parity goal.
KNOWN_DIVERGENCES = {}


def _load_docs(base: Path, names):
    docs = []
    for n in names or []:
        p = base / n
        if not p.exists():
            raise FileNotFoundError(p)
        with open(p) as f:
            for d in yaml.safe_load_all(f):
                if isinstance(d, dict):
                    docs.append(d)
    return docs


def _variables(base: Path, test_doc):
    """Load the Values doc (apis/v1alpha1 Values): globalValues,
    per-policy rule values (context variables) and per-resource values
    (e.g. request.operation)."""
    v = test_doc.get("variables") or "values.yaml"
    p = base / v
    if not p.exists():
        return {}
    with open(p) as f:
        return yaml.safe_load(f) or {}


def _rule_values(values, pname):
    out = {}
    for pv in values.get("policies") or []:
        if pv.get("name") == pname:
            for rv in pv.get("rules") or []:
                out.update(rv.get("values") or {})
    return out


def _resource_values(values, pname, res_name):
    out = dict(values.get("globalValues") or {})
    for pv in values.get("policies") or []:
        if pv.get("name") == pname:
            for rv in pv.get("resources") or []:
                if rv.get("name") in (res_name, res_name.split("/")[-1]):
                    out.update(rv.get("values") or {})
    return out


def _case_dirs():
    if not CORPUS.exists():
        return []
    return sorted(d for d in CORPUS.iterdir()
                  if (d / "kyverno-test.yaml").exists())


def _result_rows(test_doc):
    for r in test_doc.get("results") or []:
        resources = r.get("resources") or ([r["resource"]] if r.get("resource") else [])
        for res_name in resources:
            yield (r.get("policy", ""), r.get("rule", ""), res_name,
                   r.get("result", ""), r.get("kind", ""),
                   r.get("namespace", ""))


def _evaluate_dir(d: Path):
    """Returns (matches, mismatches, skipped_rows) for one fixture."""
    with open(d / "kyverno-test.yaml") as f:
        test_doc = yaml.safe_load(f)
    policy_docs = [x for x in _load_docs(d, test_doc.get("policies"))
                   if is_policy_document(x)]
    resource_docs = [x for x in _load_docs(d, test_doc.get("resources"))
                     if not is_policy_document(x)]
    policies = {}
    for pd in policy_docs:
        pol = expand_policy(ClusterPolicy.from_dict(pd))
        policies[pol.name] = pol
    values = _variables(d, test_doc)
    by_name = {}
    for rd in resource_docs:
        meta = rd.get("metadata") or {}
        name = meta.get("name", "")
        ns = meta.get("namespace", "")
        by_name.setdefault((rd.get("kind", ""), name), rd)
        by_name.setdefault((None, name), rd)
        if ns:
            by_name.setdefault((rd.get("kind", ""), f"{ns}/{name}"), rd)
            by_name.setdefault((None, f"{ns}/{name}"), rd)

    eng = Engine(data_sources=DataSources())
    verdict_cache = {}
    matches, mismatches, skipped = [], [], []
    for (pname, rule, res_name, want, kind, ns) in _result_rows(test_doc):
        if want in ("pass", "fail", "skip") and pname in policies:
            res = by_name.get((kind, res_name)) or by_name.get((None, res_name))
            if res is None:
                skipped.append((str(d.name), pname, rule, res_name,
                                "resource not found"))
                continue
            pol = policies[pname]
            if not any(r.has_validate() for r in pol.get_rules()):
                skipped.append((str(d.name), pname, rule, res_name,
                                "non-validate policy"))
                continue
            key = (pname, res_name, id(res))
            if key not in verdict_cache:
                ctx = Context()
                ctx.add_resource(res)
                # Values doc: rule values become context variables, the
                # per-resource values seed request.* (CLI store-backed
                # context, processor/policy_processor.go:75-85)
                operation = "CREATE"
                for k, v in _rule_values(values, pname).items():
                    ctx.add_variable(k, v)
                res_vals = _resource_values(values, pname, res_name)
                for k, v in res_vals.items():
                    if k == "request.operation":
                        if v:
                            ctx.add_operation(v)
                            operation = v
                    else:
                        ctx.add_variable(k, v)
                pctx = PolicyContext(policy=pol, new_resource=res,
                                     operation=operation, json_context=ctx)
                try:
                    resp = eng.validate(pctx)
                except Exception as e:
                    verdict_cache[key] = {"__error__": str(e)}
                else:
                    verdict_cache[key] = {rr.name: rr.status
                                          for rr in resp.policy_response.rules}
            verdicts = verdict_cache[key]
            if "__error__" in verdicts:
                skipped.append((str(d.name), pname, rule, res_name,
                                f"engine error: {verdicts['__error__']}"))
                continue
            # autogen rules report under autogen-<rule> for controller
            # kinds; the fixtures name the ORIGINAL rule
            got = verdicts.get(rule)
            if got is None:
                for prefix in ("autogen-", "autogen-cronjob-"):
                    got = verdicts.get(prefix + rule)
                    if got is not None:
                        break
            if got is None:
                got = "skip"  # absent = not matched ~ skip
            row_key = (d.name, pname, rule, res_name)
            if got == want:
                matches.append(row_key)
            elif row_key in KNOWN_DIVERGENCES:
                skipped.append((*row_key, "known divergence"))
            else:
                mismatches.append((*row_key, f"want {want}, got {got}"))
        else:
            skipped.append((str(d.name), pname, rule, res_name,
                            f"unsupported result type {want!r}"))
    return matches, mismatches, skipped


@pytest.mark.skipif(not CORPUS.exists(), reason="reference corpus unavailable")
def test_reference_cli_corpus_replay():
    total_matches, total_mismatches, total_skipped = [], [], []
    broken_dirs = []
    for d in _case_dirs():
        if d.name in SKIP_DIRS:
            continue
        try:
            m, mm, sk = _evaluate_dir(d)
        except Exception as e:
            broken_dirs.append((d.name, str(e)))
            continue
        total_matches += m
        total_mismatches += mm
        total_skipped += sk
    summary = (f"corpus: {len(total_matches)} matched, "
               f"{len(total_mismatches)} mismatched, "
               f"{len(total_skipped)} skipped, "
               f"{len(broken_dirs)} dirs unloadable")
    print("\n" + summary)
    for row in total_mismatches[:25]:
        print("MISMATCH:", row)
    for row in broken_dirs[:10]:
        print("BROKEN:", row)
    # breadth floor: the corpus must contribute a substantial number of
    # matched golden verdicts, and no unexplained mismatches
    assert len(total_matches) >= 100, summary
    assert not total_mismatches, summary
