"""Incremental report store (reports/store.py + reports/journal.py).

The crash-consistency contract under test:

- every delta path (apply / skip / delete / fold-fault degradation)
  leaves state bit-identical to a from-scratch ``rebuild()``;
- a journal/snapshot round trip (clean or SIGKILL-shaped) reproduces
  the digest exactly;
- each rung of the journal corruption ladder (truncated record,
  bit-flipped checksum, short header, duplicate-delta replay) recovers
  to the last good prefix with the right
  ``kyverno_reports_recoveries_total{reason}`` label — degraded, never
  a wrong report (mirrors the columnar 4-corruption-mode pattern);
- the scanner feed: an unchanged rescan does ZERO report work.
"""

import json
import os

import pytest

from kyverno_tpu.observability.metrics import global_registry as reg
from kyverno_tpu.reports import (ReportStore, configure_reports,
                                 get_report_store, reports_state,
                                 reset_reports)
from kyverno_tpu.reports import journal as jn
from kyverno_tpu.resilience.faults import (SITE_REPORTS_FOLD,
                                           SITE_REPORTS_JOURNAL,
                                           global_faults)


@pytest.fixture(autouse=True)
def _disarm():
    global_faults.disarm()
    yield
    global_faults.disarm()


def _rows(i, result="pass"):
    return [("pol-a", "r1", result), ("pol-b", "r2", "fail" if i % 3 else "pass")]


def _fill(store, n=8, sha="h0"):
    for i in range(n):
        store.apply(f"u{i}", sha, "ps1", f"ns{i % 3}", "Pod", f"pod-{i}",
                    _rows(i))


# -- fold vs rebuild (the bit-identity oracle)


def test_delta_paths_match_rebuild(tmp_path):
    s = ReportStore(directory=str(tmp_path / "r"))
    _fill(s, 10)
    s.apply("u3", "h1", "ps1", "ns0", "Pod", "pod-3", _rows(3, "fail"))
    s.delete("u7")
    s.apply("u99", "h0", "ps1", "", "Namespace", "prod",
            [("pol-a", "r1", "pass")])
    before = s.digest()
    assert s.rebuild() == before
    assert s.verify_rebuild()
    # derived counts landed where rebuild puts them
    assert s.summary()["pass"] >= 1
    assert "" in s.namespaces() or "ns0" in s.namespaces()


def test_unchanged_apply_is_zero_work(tmp_path):
    s = ReportStore(directory=str(tmp_path / "r"))
    _fill(s, 5)
    folds0 = reg.reports_fold_ops.value()
    skips0 = reg.reports_fold_skipped.value()
    recs0 = reg.reports_journal_records.value()
    jbytes = s.state()["journal_bytes"]
    _fill(s, 5)  # same (sha, ps_key) for every uid
    assert reg.reports_fold_ops.value() == folds0
    assert reg.reports_journal_records.value() == recs0
    assert reg.reports_fold_skipped.value() == skips0 + 5
    assert s.state()["journal_bytes"] == jbytes
    # a changed policy-set key is NOT zero work: reports must refresh
    s.apply("u0", "h0", "ps2", "ns0", "Pod", "pod-0", _rows(0))
    assert reg.reports_fold_ops.value() == folds0 + 1


def test_delete_unfolds_and_journal_replays(tmp_path):
    d = str(tmp_path / "r")
    s = ReportStore(directory=d)
    _fill(s, 6)
    s.delete("u2")
    assert s.state()["resources"] == 5
    digest = s.digest()
    # SIGKILL-shaped close: no compaction, the journal carries history
    s.close(compact=False)
    r0 = reg.reports_recoveries.value({"reason": jn.REASON_REPLAY})
    s2 = ReportStore(directory=d)
    assert s2.digest() == digest
    assert s2.rebuild() == digest
    assert reg.reports_recoveries.value({"reason": jn.REASON_REPLAY}) == r0 + 1


def test_clean_close_compacts_no_replay(tmp_path):
    d = str(tmp_path / "r")
    s = ReportStore(directory=d)
    _fill(s, 6)
    digest = s.digest()
    s.close()  # compacts: snapshot written, journal reset
    assert os.path.getsize(os.path.join(d, jn.JOURNAL_NAME)) == 0
    r0 = reg.reports_recoveries.value({"reason": jn.REASON_REPLAY})
    s2 = ReportStore(directory=d)
    assert s2.digest() == digest
    assert reg.reports_recoveries.value({"reason": jn.REASON_REPLAY}) == r0


def test_compaction_threshold_snapshots(tmp_path):
    d = str(tmp_path / "r")
    s = ReportStore(directory=d, journal_max_bytes=4096)
    snaps0 = reg.reports_snapshots.value()
    for i in range(200):
        s.apply(f"u{i}", f"h{i}", "ps1", "ns0", "Pod", f"pod-{i}", _rows(i))
        s.sync()
    assert reg.reports_snapshots.value() > snaps0
    assert s.state()["journal_bytes"] <= 2 * 4096
    digest = s.digest()
    s.close(compact=False)
    assert ReportStore(directory=d).digest() == digest


# -- the journal corruption ladder (mirrors test_columnar's 4 modes)


@pytest.mark.parametrize("corruption", ["truncated_record", "checksum",
                                        "short_header", "duplicate"])
def test_journal_corruption_recovers_to_prefix(tmp_path, corruption):
    d = str(tmp_path / "r")
    s = ReportStore(directory=d)
    _fill(s, 4, sha="base")  # seq 1..4
    prefix_digest_rows = dict(s._rows)  # base rows before the suffix
    s.apply("u9", "h9", "ps1", "ns9", "Pod", "pod-9", _rows(9))  # seq 5
    s.close(compact=False)
    jpath = os.path.join(d, jn.JOURNAL_NAME)
    size = os.path.getsize(jpath)
    if corruption == "truncated_record":
        # tear the LAST record: half its bytes never hit disk
        with open(jpath, "r+b") as f:
            f.truncate(size - 7)
    elif corruption == "checksum":
        # flip bytes INSIDE the last record's payload
        with open(jpath, "r+b") as f:
            f.seek(size - 12)
            f.write(b"\xff\xff\xff\xff")
    elif corruption == "short_header":
        # a torn append that only got 3 header bytes out
        with open(jpath, "ab") as f:
            f.write(b"\x01\x02\x03")
    else:  # duplicate: re-append seq 1's delta verbatim
        payload = jn.canonical(
            {"op": "put", "uid": "u0", "sha": "base", "ps": "ps1",
             "ns": "ns0", "kind": "Pod", "name": "pod-0",
             "rows": [[p, r, c] for p, r, c in _rows(0)],
             "seq": 1}).encode()
        with open(jpath, "ab") as f:
            f.write(jn.frame(payload))
    before = reg.reports_recoveries.value({"reason": corruption})
    s2 = ReportStore(directory=d)  # must not raise
    assert reg.reports_recoveries.value({"reason": corruption}) \
        == before + 1
    # recovered state is bit-identical to rebuild() over what survived
    assert s2.digest() == s2.rebuild()
    if corruption in ("duplicate", "short_header"):
        # the damage sits AFTER the last good record: every delta
        # survives (the duplicate skipped, the torn header dropped)
        assert s2.state()["resources"] == 5
    else:
        # the last record died: the surviving prefix is the 4 base rows
        assert set(s2._rows) == set(prefix_digest_rows)
    # and the journal was cleaned up (framing damage truncated in
    # place; the duplicate record swept by compaction): a second open
    # counts no new corruption recovery
    s2.close(compact=(corruption == "duplicate"))
    mid = reg.reports_recoveries.value({"reason": corruption})
    s3 = ReportStore(directory=d)
    assert reg.reports_recoveries.value({"reason": corruption}) == mid
    assert s3.digest() == s3.rebuild()


def test_corrupt_snapshot_starts_cold(tmp_path):
    d = str(tmp_path / "r")
    s = ReportStore(directory=d)
    _fill(s, 4)
    s.close()  # writes the snapshot
    with open(os.path.join(d, jn.SNAPSHOT_NAME), "w") as f:
        f.write("{not json")
    before = reg.reports_recoveries.value({"reason": jn.REASON_SNAPSHOT})
    s2 = ReportStore(directory=d)
    assert reg.reports_recoveries.value({"reason": jn.REASON_SNAPSHOT}) \
        == before + 1
    # cold, consistent, and both stale files discarded — never wrong
    assert s2.state()["resources"] == 0
    assert s2.digest() == s2.rebuild()


def test_tampered_snapshot_checksum_rejected(tmp_path):
    d = str(tmp_path / "r")
    s = ReportStore(directory=d)
    _fill(s, 3)
    s.close()
    path = os.path.join(d, jn.SNAPSHOT_NAME)
    with open(path) as f:
        body = json.load(f)
    body["rows"][0][3] = "evil-ns"  # edit without recomputing checksum
    with open(path, "w") as f:
        json.dump(body, f)
    before = reg.reports_recoveries.value({"reason": jn.REASON_SNAPSHOT})
    s2 = ReportStore(directory=d)
    assert reg.reports_recoveries.value({"reason": jn.REASON_SNAPSHOT}) \
        == before + 1
    assert s2.state()["resources"] == 0


# -- fault sites


def test_fold_fault_degrades_to_rebuild(tmp_path):
    s = ReportStore(directory=str(tmp_path / "r"))
    _fill(s, 4)
    rebuilds0 = reg.reports_rebuilds.value()
    global_faults.arm(SITE_REPORTS_FOLD, mode="raise", count=1)
    s.apply("u0", "hX", "ps1", "ns0", "Pod", "pod-0", _rows(0, "fail"))
    global_faults.disarm(SITE_REPORTS_FOLD)
    assert reg.reports_rebuilds.value() == rebuilds0 + 1
    # the degraded fold still landed the delta, bit-identically
    assert s.digest() == s.rebuild()
    assert any(r == [list(t) for t in _rows(0, "fail")][0]
               for r in s._rows["u0"][5])


def test_journal_fault_counts_append_error(tmp_path):
    s = ReportStore(directory=str(tmp_path / "r"))
    a0 = reg.reports_recoveries.value({"reason": jn.REASON_APPEND_ERROR})
    global_faults.arm(SITE_REPORTS_JOURNAL, mode="raise", count=1)
    s.apply("u0", "h0", "ps1", "ns0", "Pod", "pod-0", _rows(0))
    global_faults.disarm(SITE_REPORTS_JOURNAL)
    assert reg.reports_recoveries.value({"reason": jn.REASON_APPEND_ERROR}) \
        == a0 + 1
    # the in-memory fold still landed (degraded durability, not truth)
    assert s.state()["resources"] == 1
    assert s.digest() == s.rebuild()


def test_journal_corrupt_fault_truncates_at_replay(tmp_path):
    d = str(tmp_path / "r")
    s = ReportStore(directory=d)
    _fill(s, 2)  # two good records
    global_faults.arm(SITE_REPORTS_JOURNAL, mode="corrupt", count=1)
    s.apply("u9", "h9", "ps1", "ns9", "Pod", "pod-9", _rows(9))  # mangled
    global_faults.disarm(SITE_REPORTS_JOURNAL)
    _fill(s, 4)  # two more good records AFTER the bad one
    s.close(compact=False)
    before_ck = reg.reports_recoveries.value({"reason": jn.REASON_CHECKSUM})
    before_tr = reg.reports_recoveries.value({"reason": jn.REASON_TRUNCATED})
    s2 = ReportStore(directory=d)
    # the mangled record broke framing: replay truncated at it (either
    # rung depending on how the short write landed), prefix survived
    assert (reg.reports_recoveries.value({"reason": jn.REASON_CHECKSUM})
            + reg.reports_recoveries.value({"reason": jn.REASON_TRUNCATED})) \
        == before_ck + before_tr + 1
    assert set(s2._rows) == {"u0", "u1"}
    assert s2.digest() == s2.rebuild()


# -- process-global wiring


def test_configure_reports_singleton(tmp_path):
    reset_reports()
    assert get_report_store() is None
    assert reports_state() == {"enabled": False}
    store = configure_reports(directory=str(tmp_path / "r"))
    assert get_report_store() is store
    assert reports_state()["enabled"] is True
    assert reports_state()["persistent"] is True
    configure_reports(enabled=False)
    assert get_report_store() is None
    # in-memory mode: enabled, not persistent
    store = configure_reports()
    assert store is not None and not store.state()["persistent"]
    reset_reports()


def test_store_aggregate_matches_wgpolicy_shape(tmp_path):
    s = ReportStore()
    s.apply("u1", "h1", "ps", "prod", "Pod", "api", [("p", "r", "fail")])
    s.apply("u2", "h2", "ps", "", "Namespace", "prod", [("p", "r", "pass")])
    reports = s.aggregate()
    assert reports["prod"].kind == "PolicyReport"
    assert reports[""].kind == "ClusterPolicyReport"
    doc = reports["prod"].to_dict()
    assert doc["summary"]["fail"] == 1
    res = doc["results"][0]["resources"][0]
    assert res["name"] == "api" and res["namespace"] == "prod"
    assert res["uid"] == "u1"
