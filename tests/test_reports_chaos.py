"""Report-store crash leg (ISSUE 17 acceptance): SIGKILL mid-fold.

One REAL serve process journaling reports to disk, with a delay fault
armed at ``reports.fold`` (via KYVERNO_TPU_FAULTS) so every fold holds
the window open. The test fires a /scan and SIGKILLs the process while
folds are in flight, then asserts the crash-consistency contract:

- ``kyverno-tpu report <dir> --rebuild-check`` (offline recovery)
  exits 0 and reports delta-state == rebuild() bit-identity;
- a serve RESTART on the same directory recovers, counts the replay on
  ``kyverno_reports_recoveries_total``, and serves the recovered rows
  on ``/reports?source=store``;
- after a fresh full scan the store agrees with the live aggregator
  and the shadow verifier (rate 1.0) logs zero divergences.

Marked slow: boots two serve processes (amortized through a shared
persistent XLA cache dir).
"""

import http.client
import json
import os
import signal
import socket
import subprocess
import sys
import threading
import time

import pytest
import yaml

pytestmark = pytest.mark.slow

N_PODS = 80


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _get(port, path, timeout=30):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=timeout)
    try:
        conn.request("GET", path)
        resp = conn.getresponse()
        return resp.status, resp.read()
    finally:
        conn.close()


def _post(port, path, doc, timeout=300):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=timeout)
    try:
        conn.request("POST", path, json.dumps(doc),
                     {"Content-Type": "application/json"})
        resp = conn.getresponse()
        return resp.status, resp.read()
    finally:
        conn.close()


def _pods(n):
    return [{
        "apiVersion": "v1", "kind": "Pod",
        "metadata": {"name": f"pod-{i}", "namespace": f"ns{i % 4}",
                     "uid": f"u-{i}"},
        "spec": {"containers": [{
            "name": "c", "image": "nginx",
            **({"securityContext": {"privileged": True}}
               if i % 3 == 0 else {})}]},
    } for i in range(n)]


def _metric(text, name, **labels):
    total = 0.0
    for line in text.splitlines():
        if not line.startswith(name):
            continue
        rest = line[len(name):]
        if rest and rest[0] not in ("{", " "):
            continue
        if all(f'{k}="{v}"' in rest for k, v in labels.items()):
            try:
                total += float(line.split(" # ")[0].rsplit(" ", 1)[-1])
            except ValueError:
                pass
    return total


@pytest.fixture
def serve_procs():
    procs = []
    yield procs
    for p in procs:
        if p.poll() is None:
            p.terminate()
    for p in procs:
        try:
            p.wait(timeout=15)
        except subprocess.TimeoutExpired:
            p.kill()
            p.wait(timeout=5)


def test_sigkill_mid_fold_recovers_bit_identical(tmp_path, serve_procs):
    policy_file = tmp_path / "policy.yaml"
    policy_file.write_text(yaml.safe_dump({
        "apiVersion": "kyverno.io/v1", "kind": "ClusterPolicy",
        "metadata": {"name": "reports-chaos"},
        "spec": {"validationFailureAction": "Enforce", "rules": [{
            "name": "no-privileged",
            "match": {"any": [{"resources": {"kinds": ["Pod"]}}]},
            "validate": {"message": "no privileged",
                         "pattern": {"spec": {"containers": [
                             {"=(securityContext)":
                              {"=(privileged)": "false"}}]}}},
        }]}}))
    reports_dir = tmp_path / "reports"
    xla_cache = tmp_path / "xla"
    base_env = dict(os.environ)
    base_env.update({"JAX_PLATFORMS": "cpu",
                     "KYVERNO_TPU_XLA_CACHE_DIR": str(xla_cache)})
    base_env.pop("KYVERNO_TPU_FAULTS", None)

    def boot(metrics_port, fold_delay_s=None):
        env = dict(base_env)
        if fold_delay_s:
            # every fold sleeps: the SIGKILL lands inside the window
            # between journal-append and derived-count update
            env["KYVERNO_TPU_FAULTS"] = \
                f"reports.fold:delay:delay_s={fold_delay_s},p=1.0"
        p = subprocess.Popen(
            [sys.executable, "-m", "kyverno_tpu", "serve",
             str(policy_file),
             "--port", "0", "--metrics-port", str(metrics_port),
             "--scan-interval", "9999", "--batching",
             "--reports-dir", str(reports_dir),
             "--shadow-verify-rate", "1.0",
             "--flight-sample-rate", "1.0"],
            env=env, stdout=subprocess.DEVNULL, stderr=subprocess.PIPE,
            text=True)
        serve_procs.append(p)
        return p

    def wait_ready(p, metrics_port, timeout=300):
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if p.poll() is not None:
                raise AssertionError(
                    "serve died at boot:\n" + (p.stderr.read() or "")[-2000:])
            try:
                status, _ = _get(metrics_port, "/healthz", timeout=2)
                if status == 200:
                    return
            except OSError:
                pass
            time.sleep(0.3)
        raise AssertionError("serve never became healthy")

    port1 = _free_port()
    victim = boot(port1, fold_delay_s=0.02)
    wait_ready(victim, port1)

    for pod in _pods(N_PODS):
        status, _ = _post(port1, "/snapshot/upsert", pod)
        assert status == 200

    # fire the scan and SIGKILL while folds are in flight: 80 pods at
    # >=20ms of injected fold delay each keeps the scan alive well past
    # the kill point
    def fire_scan():
        try:
            _post(port1, "/scan", {"full": True}, timeout=30)
        except OSError:
            pass  # the kill races the response; either is fine

    t = threading.Thread(target=fire_scan, daemon=True)
    t.start()
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        if os.path.isdir(reports_dir) and os.path.exists(
                os.path.join(reports_dir, "journal.wal")) and \
                os.path.getsize(os.path.join(reports_dir, "journal.wal")) > 0:
            break
        time.sleep(0.02)
    time.sleep(0.1)  # a few more folds mid-flight
    os.kill(victim.pid, signal.SIGKILL)
    victim.wait(timeout=10)

    jpath = os.path.join(reports_dir, "journal.wal")
    assert os.path.getsize(jpath) > 0, "no deltas journaled before the kill"

    # offline recovery oracle: the CLI replays the journal and asserts
    # delta state == rebuild() bit-identity (exit 1 on mismatch)
    cli = subprocess.run(
        [sys.executable, "-m", "kyverno_tpu", "report", str(reports_dir),
         "--rebuild-check", "--json"],
        env=base_env, capture_output=True, text=True, timeout=120)
    assert cli.returncode == 0, cli.stderr[-2000:]
    doc = json.loads(cli.stdout)
    assert doc["rebuild_identical"] is True
    assert doc["state"]["resources"] > 0
    recovered_resources = doc["state"]["resources"]
    recovered_summary = doc["summary"]

    # restart on the SAME directory (no fault this time): the replay
    # recovery is counted and the recovered rows are served
    port2 = _free_port()
    survivor = boot(port2)
    wait_ready(survivor, port2)

    status, body = _get(port2, "/metrics")
    assert status == 200
    text = body.decode()
    assert _metric(text, "kyverno_reports_recoveries_total") > 0, \
        "unclean shutdown must be counted as a recovery"
    assert _metric(text, "kyverno_reports_resources") \
        == recovered_resources

    status, body = _get(port2, "/reports?source=store")
    assert status == 200
    served = json.loads(body)
    served_rows = sum(len(r.get("results", [])) for r in served.values())
    assert served_rows == sum(recovered_summary.values())

    # a fresh full scan over the same snapshot-fed pods converges the
    # store on the live truth; shadow verifier at rate 1.0 throughout
    for pod in _pods(N_PODS):
        status, _ = _post(port2, "/snapshot/upsert", pod)
        assert status == 200
    status, body = _post(port2, "/scan", {"full": True})
    assert status == 200
    assert json.loads(body)["scanned"] == N_PODS

    status, body = _get(port2, "/debug/state")
    assert status == 200
    dbg = json.loads(body)
    assert dbg["reports"]["enabled"] is True
    assert dbg["reports"]["resources"] == N_PODS

    def checks():
        _, b = _get(port2, "/metrics")
        return _metric(b.decode(), "kyverno_verification_checks_total",
                       result="match")

    deadline = time.monotonic() + 60
    while time.monotonic() < deadline:
        if checks() > 0:
            break
        time.sleep(0.5)
    _, body = _get(port2, "/metrics")
    text = body.decode()
    assert _metric(text, "kyverno_verification_divergence_total") == 0
    assert _metric(text, "kyverno_verification_checks_total",
                   result="match") > 0
    for fam in ("kyverno_reports_resources", "kyverno_reports_fold_ops_total",
                "kyverno_reports_journal_records_total",
                "kyverno_reports_recoveries_total"):
        assert f"# TYPE {fam} " in text, fam
