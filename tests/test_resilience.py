"""Resilience layer: circuit breaker, retry/backoff, fault injection,
encode quarantine, shutdown drain, and failurePolicy deadline mapping.

Every failure mode is exercised through the fault registry so the
chaos behavior asserted here is deterministic and replayable."""

import threading
import time

import pytest

from kyverno_tpu.observability.metrics import MetricsRegistry
from kyverno_tpu.resilience import (CLOSED, HALF_OPEN, OPEN, CircuitBreaker,
                                    Deadline, FaultConfigError, FaultInjected,
                                    FaultRegistry, PermanentError, RetryPolicy,
                                    global_faults, retry_call, tpu_breaker)


@pytest.fixture(autouse=True)
def _clean_faults_and_breaker():
    """Faults and the shared TPU breaker are process-global: leave no
    chaos armed for the rest of the suite."""
    global_faults.disarm()
    tpu_breaker().reset()
    yield
    global_faults.disarm()
    tpu_breaker().reset()


# ---------------------------------------------------------------------------
# circuit breaker


def test_breaker_trips_after_consecutive_failures_and_half_open_recovers():
    now = [0.0]
    b = CircuitBreaker(name="t1", failure_threshold=3, reset_timeout_s=5.0,
                       clock=lambda: now[0], metrics=MetricsRegistry())
    assert b.state == CLOSED
    b.record_failure()
    b.record_failure()
    b.record_success()  # success resets the consecutive count
    b.record_failure()
    b.record_failure()
    assert b.state == CLOSED
    b.record_failure()
    assert b.state == OPEN
    assert not b.allow()           # open: no device attempts
    now[0] = 4.9
    assert not b.allow()
    now[0] = 5.1
    assert b.allow()               # one half-open probe admitted
    assert b.state == HALF_OPEN
    assert not b.allow()           # but only one
    b.record_success()
    assert b.state == CLOSED
    assert b.allow()


def test_breaker_bare_reset_restores_constructor_tuning():
    # the process-wide breaker is shared across tests: a bare reset()
    # must restore constructor tuning, or one test's threshold=1 leaks
    # into every later test in the same process
    b = CircuitBreaker(name="t-reset", failure_threshold=3,
                       reset_timeout_s=10.0, metrics=MetricsRegistry())
    b.reset(failure_threshold=1, reset_timeout_s=0.05)
    assert b.failure_threshold == 1 and b.reset_timeout_s == 0.05
    b.record_failure()
    assert b.state == OPEN
    b.reset()
    assert b.state == CLOSED
    assert b.failure_threshold == 3 and b.reset_timeout_s == 10.0


def test_breaker_half_open_failure_reopens():
    now = [0.0]
    b = CircuitBreaker(name="t2", failure_threshold=1, reset_timeout_s=1.0,
                       clock=lambda: now[0], metrics=MetricsRegistry())
    b.record_failure()
    assert b.state == OPEN
    now[0] = 1.5
    assert b.allow()
    b.record_failure()             # probe failed: straight back to OPEN
    assert b.state == OPEN
    assert not b.allow()
    now[0] = 2.4                   # reset timer restarted at reopen
    assert not b.allow()
    now[0] = 2.6
    assert b.allow()


def test_breaker_metrics_state_and_transitions():
    reg = MetricsRegistry()
    b = CircuitBreaker(name="m", failure_threshold=1, reset_timeout_s=0.0,
                       metrics=reg)
    b.record_failure()
    assert b.allow()
    b.record_success()
    text = reg.exposition()
    assert 'kyverno_tpu_breaker_state{breaker="m"} 0' in text
    assert ('kyverno_tpu_breaker_transitions_total'
            '{breaker="m",from="closed",to="open"} 1.0') in text
    assert ('kyverno_tpu_breaker_transitions_total'
            '{breaker="m",from="half_open",to="closed"} 1.0') in text


# ---------------------------------------------------------------------------
# retry / backoff / deadline


def test_retry_recovers_after_transient_failures():
    calls = {"n": 0}

    def flaky():
        calls["n"] += 1
        if calls["n"] < 3:
            raise RuntimeError("transient")
        return "ok"

    out = retry_call(flaky, RetryPolicy(max_attempts=3, base_delay_s=0.0),
                     metrics=MetricsRegistry())
    assert out == "ok" and calls["n"] == 3


def test_retry_exhausts_attempts_and_raises_last_error():
    calls = {"n": 0}

    def always():
        calls["n"] += 1
        raise ValueError(f"boom {calls['n']}")

    with pytest.raises(ValueError, match="boom 3"):
        retry_call(always, RetryPolicy(max_attempts=3, base_delay_s=0.0),
                   metrics=MetricsRegistry())
    assert calls["n"] == 3


def test_retry_permanent_error_skips_remaining_attempts():
    # a 404-style deterministic failure must NOT pay 3 backend calls
    # plus backoff on every admission — PermanentError opts out
    calls = {"n": 0}

    class NotFound(PermanentError):
        pass

    def missing():
        calls["n"] += 1
        raise NotFound("no such object")

    with pytest.raises(NotFound):
        retry_call(missing, RetryPolicy(max_attempts=3, base_delay_s=0.0),
                   metrics=MetricsRegistry())
    assert calls["n"] == 1


def test_retry_backoff_is_exponential_with_bounded_jitter():
    policy = RetryPolicy(max_attempts=4, base_delay_s=0.1, max_delay_s=10.0,
                         multiplier=2.0, jitter=0.5, deadline_s=None)
    sleeps = []

    def always():
        raise RuntimeError("x")

    with pytest.raises(RuntimeError):
        retry_call(always, policy, sleep=sleeps.append,
                   metrics=MetricsRegistry())
    assert len(sleeps) == 3
    for i, s in enumerate(sleeps):
        nominal = 0.1 * 2.0 ** i
        assert nominal * 0.5 <= s <= nominal * 1.5


def test_retry_respects_deadline_budget():
    """A backoff the remaining budget cannot cover is not slept: the
    loop fails fast instead of waking up past the caller's deadline."""
    now = [0.0]
    sleeps = []

    def sleep(s):
        sleeps.append(s)
        now[0] += s

    calls = {"n": 0}

    def always():
        calls["n"] += 1
        now[0] += 0.4  # each attempt costs 0.4s of the 1s budget
        raise RuntimeError("slow backend")

    policy = RetryPolicy(max_attempts=10, base_delay_s=0.3, multiplier=2.0,
                         jitter=0.0, deadline_s=1.0)
    with pytest.raises(RuntimeError):
        retry_call(always, policy, clock=lambda: now[0], sleep=sleep,
                   metrics=MetricsRegistry())
    assert calls["n"] == 2  # attempt, 0.3s backoff, attempt, budget gone
    assert sleeps == [0.3]


def test_deadline_remaining_and_expiry():
    now = [0.0]
    d = Deadline(2.0, clock=lambda: now[0])
    assert d.remaining() == pytest.approx(2.0)
    now[0] = 2.5
    assert d.expired()
    assert Deadline(None).remaining() == float("inf")


# ---------------------------------------------------------------------------
# fault registry


def test_fault_registry_count_trigger_then_heals():
    r = FaultRegistry()
    r.arm("gctx.refresh", mode="raise", count=2)
    for _ in range(2):
        with pytest.raises(FaultInjected):
            r.fire("gctx.refresh")
    r.fire("gctx.refresh")  # healed after N triggers
    assert r.armed()["gctx.refresh"].fired == 2


def test_fault_registry_probability_is_seeded_deterministic():
    def run(seed):
        r = FaultRegistry()
        r.arm("tpu.dispatch", mode="raise", p=0.5, seed=seed)
        out = []
        for _ in range(32):
            try:
                r.fire("tpu.dispatch")
                out.append(0)
            except FaultInjected:
                out.append(1)
        return out

    assert run(7) == run(7)      # replayable chaos
    assert 0 < sum(run(7)) < 32  # actually probabilistic


def test_fault_registry_corrupt_mode_mangles_result_shape():
    import numpy as np

    r = FaultRegistry()
    r.arm("tpu.dispatch", mode="corrupt", count=1)
    r.fire("tpu.dispatch")  # corrupt never fires on the raise hook
    table = np.zeros((3, 8))
    assert r.corrupt("tpu.dispatch", table).shape == (3, 7)
    # trigger consumed: the next result passes through untouched
    assert r.corrupt("tpu.dispatch", table).shape == (3, 8)


def test_fault_registry_env_syntax_roundtrip():
    r = FaultRegistry()
    n = r.arm_from_string(
        "tpu.dispatch:corrupt:p=0.3,seed=42; serving.flush:delay:delay_s=0.2;"
        "gctx.refresh:raise:count=3")
    assert n == 3
    armed = r.armed()
    assert armed["tpu.dispatch"].p == 0.3 and armed["tpu.dispatch"].seed == 42
    assert armed["serving.flush"].mode == "delay"
    assert armed["serving.flush"].delay_s == 0.2
    assert armed["gctx.refresh"].count == 3
    with pytest.raises(FaultConfigError):
        r.arm_from_string("not.a.site:raise")
    with pytest.raises(FaultConfigError):
        r.arm_from_string("tpu.dispatch")  # needs site:mode
    with pytest.raises(FaultConfigError):
        r.arm("tpu.dispatch", mode="explode")
    with pytest.raises(FaultConfigError):
        # corrupt only applies where the result is filtered: arming it
        # at a raise/delay-only site would silently inject NOTHING
        r.arm("gctx.refresh", mode="corrupt")


# ---------------------------------------------------------------------------
# TPU engine: breaker-gated dispatch + encode quarantine

POLICY_DOC = {
    "apiVersion": "kyverno.io/v1", "kind": "ClusterPolicy",
    "metadata": {"name": "no-priv"},
    "spec": {"validationFailureAction": "Enforce", "rules": [{
        "name": "check-privileged",
        "match": {"any": [{"resources": {"kinds": ["Pod"]}}]},
        "validate": {"message": "privileged denied",
                     "pattern": {"spec": {"containers": [
                         {"=(securityContext)": {"=(privileged)": "false"}}]}}},
    }]},
}


def _pod(name, priv):
    return {"apiVersion": "v1", "kind": "Pod",
            "metadata": {"name": name, "namespace": "default"},
            "spec": {"containers": [{
                "name": "c", "image": "nginx",
                "securityContext": {"privileged": priv}}]}}


def _mk_engine():
    from kyverno_tpu.api.policy import ClusterPolicy
    from kyverno_tpu.tpu.engine import TpuEngine

    return TpuEngine([ClusterPolicy.from_dict(POLICY_DOC)])


def test_tpu_dispatch_fault_trips_breaker_and_verdicts_stay_identical(
        no_verdict_cache):
    eng = _mk_engine()
    eng.breaker.reset(failure_threshold=2, reset_timeout_s=60.0)
    resources = [_pod("a", True), _pod("b", False)]
    want = eng.scan(resources).verdicts.tolist()

    global_faults.arm("tpu.dispatch", mode="raise", p=1.0)
    assert eng.scan(resources).verdicts.tolist() == want  # failure 1
    assert eng.breaker.state == CLOSED
    assert eng.scan(resources).verdicts.tolist() == want  # failure 2: trip
    assert eng.breaker.state == OPEN
    # open: the device is not even attempted, yet verdicts are identical
    fired_before = global_faults.armed()["tpu.dispatch"].fired
    assert eng.scan(resources).verdicts.tolist() == want
    assert global_faults.armed()["tpu.dispatch"].fired == fired_before


def test_tpu_dispatch_corrupt_shape_is_a_device_failure(no_verdict_cache):
    eng = _mk_engine()
    eng.breaker.reset(failure_threshold=1, reset_timeout_s=0.0)
    resources = [_pod("a", True), _pod("b", False)]
    want = eng.scan(resources).verdicts.tolist()
    global_faults.arm("tpu.dispatch", mode="corrupt", count=1)
    assert eng.scan(resources).verdicts.tolist() == want  # mangled -> scalar
    assert eng.breaker.state == OPEN
    # reset_timeout 0: next scan is the half-open probe and succeeds
    assert eng.scan(resources).verdicts.tolist() == want
    assert eng.breaker.state == CLOSED


def test_hostile_resource_is_quarantined_not_fatal():
    """Satellite: a resource that fails encoding must not abort the
    batch — it completes on the scalar engine; the rest of the batch
    still evaluates normally."""
    eng = _mk_engine()
    hostile = {"kind": b"bytes-break-encoding", "metadata": {"name": "h"}}
    result = eng.scan([_pod("a", True), hostile, _pod("b", False)])
    row = result.rules.index(("no-priv", "check-privileged"))
    from kyverno_tpu.tpu.engine import VERDICT_NAMES

    assert VERDICT_NAMES[int(result.verdicts[row, 0])] == "fail"
    assert VERDICT_NAMES[int(result.verdicts[row, 1])] == "not_matched"
    assert VERDICT_NAMES[int(result.verdicts[row, 2])] == "pass"


def test_hostile_resource_scalar_failure_yields_per_rule_error():
    """When even the scalar engine cannot evaluate the quarantined
    resource, every rule reports ERROR — never an exception."""
    eng = _mk_engine()
    hostile = {"kind": b"x", "metadata": "not-a-dict"}
    result = eng.scan([hostile, _pod("ok", False)])
    from kyverno_tpu.tpu.evaluator import ERROR, PASS

    assert (result.verdicts[:, 0] == ERROR).all()
    row = result.rules.index(("no-priv", "check-privileged"))
    assert result.verdicts[row, 1] == PASS


def test_background_scan_survives_hostile_snapshot_resource():
    """Satellite: the scan loop must keep reporting on healthy
    resources when the snapshot holds a resource that breaks
    encoding (NaN metadata.name survives JSON but not the encoder)."""
    from kyverno_tpu.api.policy import ClusterPolicy
    from kyverno_tpu.cluster import (BackgroundScanService, ClusterSnapshot,
                                     PolicyCache, ReportAggregator)

    snap = ClusterSnapshot()
    cache = PolicyCache()
    cache.set(ClusterPolicy.from_dict(POLICY_DOC))
    agg = ReportAggregator()
    svc = BackgroundScanService(snap, cache, agg)
    snap.upsert(_pod("good", True))
    snap.upsert({"apiVersion": "v1", "kind": "Pod",
                 "metadata": {"name": float("nan"), "namespace": "default",
                              "uid": "hostile-uid"}})
    n = svc.scan_once()
    assert n == 2  # both scanned, nothing aborted
    summary = agg.summary()
    assert summary.get("fail", 0) >= 1  # the good pod's verdict landed


# ---------------------------------------------------------------------------
# context loaders: retry with backoff at the backend sites


def _ctx(resource):
    from kyverno_tpu.engine.context import Context

    ctx = Context()
    ctx.add_resource(resource)
    return ctx


def test_api_call_context_retries_through_transient_faults():
    from kyverno_tpu.engine.contextloaders import (DataSources,
                                                   load_context_entries)

    calls = {"n": 0}

    def backend(spec):
        calls["n"] += 1
        return {"items": [1, 2, 3]}

    # the first two ATTEMPTS fail via the armed site; the third lands
    global_faults.arm("context.api_call", mode="raise", count=2)
    ctx = _ctx(_pod("p", False))
    sources = DataSources(
        api_call=backend,
        retry=RetryPolicy(max_attempts=3, base_delay_s=0.001, deadline_s=2.0))
    load_context_entries(
        ctx, [{"name": "pods", "apiCall": {"urlPath": "/api/v1/pods"}}],
        sources, deferred=False)
    assert ctx.query("pods.items") == [1, 2, 3]
    assert calls["n"] == 1  # fault fired before the backend on 2 attempts


def test_api_call_retries_exhausted_surfaces_error_not_hang():
    from kyverno_tpu.engine.contextloaders import (DataSources,
                                                   load_context_entries)

    global_faults.arm("context.api_call", mode="raise", p=1.0)
    ctx = _ctx(_pod("p", False))
    sources = DataSources(
        api_call=lambda spec: {"x": 1},
        retry=RetryPolicy(max_attempts=3, base_delay_s=0.001, deadline_s=1.0))
    t0 = time.monotonic()
    with pytest.raises(FaultInjected):
        load_context_entries(
            ctx, [{"name": "pods", "apiCall": {"urlPath": "/x"}}],
            sources, deferred=False)
    assert time.monotonic() - t0 < 1.0  # bounded, inside the budget


def test_batch_scoped_backend_poisoning_fails_fast_after_first_exhaust():
    from kyverno_tpu.engine.contextloaders import (ContextLoaderError,
                                                   DataSources,
                                                   load_context_entries)

    calls = {"n": 0}

    def dead_backend(spec):
        calls["n"] += 1
        raise RuntimeError("connection refused")

    sources = DataSources(
        api_call=dead_backend,
        retry=RetryPolicy(max_attempts=3, base_delay_s=0.001, deadline_s=2.0))
    sources.begin_batch()
    entry = [{"name": "pods", "apiCall": {"urlPath": "/api/v1/pods"}}]
    with pytest.raises(RuntimeError):  # first cell pays the retries
        load_context_entries(_ctx(_pod("a", False)), entry, sources,
                             deferred=False)
    assert calls["n"] == 3
    with pytest.raises(ContextLoaderError, match="marked down"):
        load_context_entries(_ctx(_pod("b", False)), entry, sources,
                             deferred=False)
    assert calls["n"] == 3  # poisoned: no further backend calls
    sources.end_batch()  # batch over: loads outside a batch retry again
    with pytest.raises(RuntimeError):
        load_context_entries(_ctx(_pod("c", False)), entry, sources,
                             deferred=False)
    assert calls["n"] == 6


def test_backend_permanent_error_neither_retried_nor_poisoning():
    from kyverno_tpu.engine.contextloaders import (DataSources,
                                                   load_context_entries)

    calls = {"n": 0}

    def backend(spec):
        calls["n"] += 1
        if spec.get("urlPath") == "/missing":
            raise PermanentError("404 not found")
        return {"ok": True}

    sources = DataSources(
        api_call=backend,
        retry=RetryPolicy(max_attempts=3, base_delay_s=0.001, deadline_s=2.0))
    sources.begin_batch()
    with pytest.raises(PermanentError):  # one attempt, no backoff
        load_context_entries(
            _ctx(_pod("a", False)),
            [{"name": "x", "apiCall": {"urlPath": "/missing"}}],
            sources, deferred=False)
    assert calls["n"] == 1
    # a per-cell deterministic failure must NOT poison the site
    ctx = _ctx(_pod("b", False))
    load_context_entries(
        ctx, [{"name": "y", "apiCall": {"urlPath": "/present"}}],
        sources, deferred=False)
    assert ctx.query("y.ok") is True


def test_image_data_context_retries_flaky_backend():
    from kyverno_tpu.engine.contextloaders import (DataSources,
                                                   load_context_entries)

    calls = {"n": 0}

    def image_backend(ref):
        calls["n"] += 1
        if calls["n"] < 3:
            raise RuntimeError("registry 503")
        return {"manifest": {"config": {"digest": "sha256:abc"}}}

    ctx = _ctx(_pod("p", False))
    sources = DataSources(
        image_data=image_backend,
        retry=RetryPolicy(max_attempts=3, base_delay_s=0.001, deadline_s=2.0))
    load_context_entries(
        ctx, [{"name": "img", "imageRegistry": {"reference": "nginx"}}],
        sources, deferred=False)
    assert calls["n"] == 3
    assert ctx.query("img.manifest.config.digest") == "sha256:abc"


# ---------------------------------------------------------------------------
# gctx external-API entry driven through the fault registry (satellite)


def test_gctx_entry_fault_registry_stale_error_recovery_cycle():
    from kyverno_tpu.globalcontext import EntryError, ExternalApiEntry
    from kyverno_tpu.globalcontext.types import ExternalAPICallSpec

    now = [0.0]
    entry = ExternalApiEntry(
        ExternalAPICallSpec(url_path="/x", refresh_interval_s=10),
        lambda spec: {"healthy": True},
        clock=lambda: now[0],
        retry=RetryPolicy(max_attempts=2, base_delay_s=0.0, deadline_s=5.0),
        sleep=lambda s: None)
    assert entry.get() == {"healthy": True}

    # backend fails every attempt for 3 polls (2 retry attempts each)
    global_faults.arm("gctx.refresh", mode="raise", count=6)
    now[0] = 11.0
    assert entry.get() == {"healthy": True}  # stale-served
    now[0] = 22.0
    assert entry.get() == {"healthy": True}  # still inside TTL (30s)
    now[0] = 33.0
    with pytest.raises(EntryError):          # past TTL: error state
        entry.get()
    # fault budget exhausted = backend healed; next poll recovers
    now[0] = 44.0
    assert entry.get() == {"healthy": True}


def test_gctx_concurrent_readers_single_flight_stale_serve():
    """With a stale entry and a slow-failing backend, exactly ONE
    reader pays the refresh; the others serve the cached value
    immediately instead of piling their own retry loops onto a backend
    that is already down."""
    from kyverno_tpu.globalcontext import ExternalApiEntry
    from kyverno_tpu.globalcontext.types import ExternalAPICallSpec

    gate = threading.Event()
    calls = []

    def executor(spec):
        calls.append(1)
        if len(calls) == 1:
            return {"v": 1}
        gate.wait(5.0)  # slow failure: holds the refresh in flight
        raise RuntimeError("backend down")

    entry = ExternalApiEntry(
        ExternalAPICallSpec(url_path="/x", refresh_interval_s=0.01),
        executor,
        retry=RetryPolicy(max_attempts=1, base_delay_s=0.0, deadline_s=5.0),
        stale_ttl_s=60.0)  # keep the refresher inside the stale window
    assert entry.get() == {"v": 1}
    time.sleep(0.02)  # entry is now stale

    results = []
    lock = threading.Lock()

    def reader():
        out = entry.get()
        with lock:
            results.append(out)

    threads = [threading.Thread(target=reader) for _ in range(8)]
    for t in threads:
        t.start()
    # while ONE refresh is wedged on the gate, the other 7 readers must
    # come back with the stale value almost immediately
    deadline = time.monotonic() + 2.0
    while len(results) < 7 and time.monotonic() < deadline:
        time.sleep(0.005)
    assert len(results) >= 7, "readers blocked behind the in-flight refresh"
    assert all(r == {"v": 1} for r in results)
    assert len(calls) == 2, "more than one refresh ran for one window"
    gate.set()
    for t in threads:
        t.join(timeout=5.0)
    assert len(results) == 8 and all(r == {"v": 1} for r in results)


def test_gctx_cold_entry_wait_is_bounded_when_first_fetch_hangs():
    """A hung executor on the FIRST fetch (no data to stale-serve) must
    not hang every other reader: the cold-entry wait is bounded by the
    retry deadline budget and then surfaces the error state."""
    from kyverno_tpu.globalcontext import EntryError, ExternalApiEntry
    from kyverno_tpu.globalcontext.types import ExternalAPICallSpec

    hang = threading.Event()

    def wedged_executor(spec):
        hang.wait(10.0)  # hung socket, no client timeout
        raise RuntimeError("too late")

    entry = ExternalApiEntry(
        ExternalAPICallSpec(url_path="/x", refresh_interval_s=10),
        wedged_executor,
        retry=RetryPolicy(max_attempts=1, base_delay_s=0.0, deadline_s=0.2))
    def first_reader():
        try:
            entry.get()
        except Exception:
            pass  # the hung fetch eventually errors; not under test

    refresher = threading.Thread(target=first_reader)
    refresher.start()
    time.sleep(0.05)  # let the refresher wedge inside the executor
    t0 = time.monotonic()
    with pytest.raises(EntryError, match="in flight"):
        entry.get()
    assert time.monotonic() - t0 < 5.0  # bounded by deadline_s + 1
    hang.set()
    refresher.join(timeout=5.0)


def test_gctx_store_refresh_all_keeps_polling_through_faults():
    from kyverno_tpu.globalcontext import GlobalContextStore

    store = GlobalContextStore(api_executor=lambda spec: {"v": 1})
    assert store.apply({
        "apiVersion": "kyverno.io/v2alpha1", "kind": "GlobalContextEntry",
        "metadata": {"name": "ext"},
        "spec": {"apiCall": {"urlPath": "/x", "refreshInterval": "1s"}}}) == []
    store.refresh_all()
    assert store["ext"] == {"v": 1}
    global_faults.arm("gctx.refresh", mode="raise", p=1.0)
    store.refresh_all()              # poll fails...
    assert store["ext"] == {"v": 1}  # ...reads serve last-known-good
    global_faults.disarm("gctx.refresh")
    store.refresh_all()
    assert store["ext"] == {"v": 1}


# ---------------------------------------------------------------------------
# serving pipeline: shutdown drain + flush faults


def test_shutdown_with_wedged_evaluator_resolves_queued_waiters():
    """Satellite regression: stop() must leave NO queued future
    unresolved — queued requests resolve via the scalar fallback even
    when the flusher is wedged on a stuck evaluator."""
    from kyverno_tpu.serving import AdmissionPipeline, BatchConfig

    wedged = threading.Event()
    release = threading.Event()

    def stuck(payloads):
        wedged.set()
        release.wait(30)
        return [("batched", p) for p in payloads if p is not None]

    p = AdmissionPipeline(
        stuck, scalar_fallback=lambda payload: ("scalar", payload),
        config=BatchConfig(max_batch_size=1, max_wait_ms=1.0, min_bucket=1,
                           eval_grace_s=0.2))
    results = {}
    threads = [threading.Thread(target=lambda i=i: results.update(
        {i: p.submit(f"r{i}", deadline_ms=60_000)})) for i in range(3)]
    threads[0].start()
    assert wedged.wait(5)          # r0 is in-flight on the stuck evaluator
    threads[1].start()
    threads[2].start()
    time.sleep(0.1)                # r1, r2 are queued behind it
    p.stop()                       # join times out (0.2s), drain kicks in
    release.set()                  # unwedge so r0 also completes
    for t in threads:
        t.join(timeout=10)
        assert not t.is_alive()
    assert results[0] == ("batched", "r0")
    assert results[1] == ("scalar", "r1")
    assert results[2] == ("scalar", "r2")
    assert p.queue.depth() == 0


def test_shutdown_drain_without_fallback_resolves_with_error():
    from kyverno_tpu.serving import AdmissionPipeline, BatchConfig
    from kyverno_tpu.serving.queue import QueuedRequest

    p = AdmissionPipeline(lambda payloads: [], config=BatchConfig())
    p.stop()
    # simulate a stranded entry (wedged-flusher shape) and re-drain
    req = QueuedRequest("r", time.monotonic(), time.monotonic() + 60)
    p.queue._items.append(req)
    for leftover in p.queue.drain_all():
        leftover.resolve(RuntimeError("stopped"))
    assert req.event.is_set()


def test_serving_flush_fault_resolves_per_failure_policy():
    """An injected flush failure must come back as a failurePolicy
    decision (deny on the fail class, allow on ignore) — never an
    unhandled exception out of the webhook handler."""
    from tests.test_serving import _mk_handlers, _pod as s_pod, _review

    handlers = _mk_handlers(batching=True, max_batch_size=4, max_wait_ms=5.0)
    try:
        ok = handlers.validate(_review(s_pod("w", False), "warm"))
        assert ok["response"]["allowed"] is True
        global_faults.arm("serving.flush", mode="raise", p=1.0)
        out = handlers.validate(_review(s_pod("p1", True), "u1"))
        assert out["response"]["allowed"] is False  # "all" fails closed
        assert "evaluation error" in out["response"]["status"]["message"]
        out = handlers.validate(_review(s_pod("p2", True), "u2"), "ignore")
        assert out["response"]["allowed"] is True   # Ignore class allows
        global_faults.disarm("serving.flush")
        out = handlers.validate(_review(s_pod("p3", True), "u3"))
        assert out["response"]["allowed"] is False
        assert "privileged" in out["response"]["status"]["message"]
    finally:
        handlers.pipeline.stop()
        handlers.batcher.stop()


# ---------------------------------------------------------------------------
# webhook deadline budget -> failurePolicy


def test_request_budget_overrun_resolves_per_failure_policy():
    from kyverno_tpu.api.policy import ClusterPolicy
    from kyverno_tpu.cluster import PolicyCache
    from kyverno_tpu.webhooks import build_handlers
    from tests.test_serving import _review, _pod as s_pod

    cache = PolicyCache()
    cache.set(ClusterPolicy.from_dict(POLICY_DOC))
    handlers = build_handlers(cache, request_timeout_s=0.0)
    try:
        out = handlers.validate(_review(s_pod("p", True), "u1"))
        assert out["response"]["allowed"] is False
        assert "evaluation error" in out["response"]["status"]["message"]
        out = handlers.validate(_review(s_pod("p", True), "u2"), "ignore")
        assert out["response"]["allowed"] is True
    finally:
        handlers.batcher.stop()


def test_force_failure_policy_ignore_toggle_fails_open():
    from kyverno_tpu.api.policy import ClusterPolicy
    from kyverno_tpu.cluster import PolicyCache
    from kyverno_tpu.config import Toggles
    from kyverno_tpu.webhooks import build_handlers
    from tests.test_serving import _review, _pod as s_pod

    cache = PolicyCache()
    cache.set(ClusterPolicy.from_dict(POLICY_DOC))
    handlers = build_handlers(
        cache, request_timeout_s=0.0,
        toggles=Toggles(force_failure_policy_ignore="true"))
    try:
        out = handlers.validate(_review(s_pod("p", True), "u1"))
        assert out["response"]["allowed"] is True  # forced fail-open
    finally:
        handlers.batcher.stop()
