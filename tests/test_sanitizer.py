"""Dynamic lock-order sanitizer (devtools/sanitizer.py).

The AB/BA fixture is the canonical seeded deadlock: two threads take
two locks in opposite orders, SEQUENCED so the test never actually
deadlocks — the sanitizer must still report the cycle, because the
order inversion is the bug and the hang is just the unlucky schedule.
"""

import json
import os
import subprocess
import sys
import threading

import pytest

from kyverno_tpu.devtools import sanitizer

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture
def sanitized():
    """Install for the test, restore the real factories after. Locks
    created while installed stay wrapped but harmless."""
    sanitizer.install()
    sanitizer.reset()
    yield sanitizer
    sanitizer.reset()
    sanitizer.uninstall()


def _run_thread(fn):
    t = threading.Thread(target=fn)
    t.start()
    t.join(timeout=10)
    assert not t.is_alive()


def test_seeded_ab_ba_inversion_reports_cycle(sanitized):
    a, b = threading.Lock(), threading.Lock()

    def t1():
        with a:
            with b:
                pass

    def t2():
        with b:
            with a:
                pass

    _run_thread(t1)
    _run_thread(t2)
    rep = sanitizer.report()
    assert len(rep["cycles"]) == 1
    cycle = rep["cycles"][0]
    assert len(cycle) == 2  # both directions of the inversion
    # each edge carries BOTH acquisition stacks for the report
    for edge in cycle:
        assert edge["from_stack"] and edge["to_stack"]
        assert any("test_sanitizer" in fr for fr in edge["to_stack"])


def test_consistent_order_is_clean(sanitized):
    a, b, c = threading.Lock(), threading.Lock(), threading.Lock()

    def t():
        with a:
            with b:
                with c:
                    pass

    for _ in range(3):
        _run_thread(t)
    rep = sanitizer.report()
    assert rep["cycles"] == []
    assert rep["edges"] >= 3  # a->b, a->c, b->c


def test_three_lock_rotation_cycle(sanitized):
    a, b, c = threading.Lock(), threading.Lock(), threading.Lock()
    for first, second in ((a, b), (b, c), (c, a)):
        def t(x=first, y=second):
            with x:
                with y:
                    pass
        _run_thread(t)
    rep = sanitizer.report()
    assert len(rep["cycles"]) == 1
    assert len(rep["cycles"][0]) == 3


def test_rlock_reentrancy_no_self_edge(sanitized):
    r = threading.RLock()

    def t():
        with r:
            with r:  # re-entrant: must not create an edge or a cycle
                pass

    _run_thread(t)
    rep = sanitizer.report()
    assert rep["edges"] == 0 and rep["cycles"] == []


def test_condition_wait_releases_tracking(sanitized):
    """cv.wait() releases the lock while sleeping; the held-set must
    reflect that or every lock taken inside a waiter body would edge
    against the cv's lock."""
    cv = threading.Condition()
    other = threading.Lock()
    woke = []

    def waiter():
        with cv:
            cv.wait(timeout=5)
            woke.append(1)

    t = threading.Thread(target=waiter)
    t.start()
    # while the waiter sleeps, its thread must NOT be considered
    # holding the cv lock; this main-thread pairing stays edge-free
    import time

    time.sleep(0.1)
    with other:
        pass
    with cv:
        cv.notify_all()
    t.join(timeout=10)
    assert woke
    rep = sanitizer.report()
    assert rep["cycles"] == []


def test_cv_wait_at_depth_keeps_lock_tracked(sanitized):
    """Regression: cv.wait() at RLock recursion depth 2 restored the
    lock with tracking count 1, so the first post-wait release dropped
    it from the held set while still held — hiding every order edge
    (and dispatch hold) in that window."""
    cv = threading.Condition()
    other = threading.Lock()

    def t():
        with cv:
            with cv:
                cv.wait(timeout=0.05)
            # depth back to 1: cv's lock is STILL held here
            with other:
                pass

    _run_thread(t)
    rep = sanitizer.report()
    assert rep["edges"] >= 1  # the cvlock->other edge must exist


def test_dispatch_under_lock_reported_with_stacks(sanitized):
    lk = threading.Lock()
    with lk:
        sanitizer.note_device_dispatch()
    rep = sanitizer.report()
    assert len(rep["dispatch_violations"]) == 1
    v = rep["dispatch_violations"][0]
    assert v["locks"][0]["acquire_stack"]
    assert v["dispatch_stack"]


def test_dispatch_without_lock_clean(sanitized):
    sanitizer.note_device_dispatch()
    assert sanitizer.report()["dispatch_violations"] == []


def test_allowlisted_lock_site_reports_separately(sanitized):
    """The lifecycle compile lock intentionally spans the XLA warm
    dispatch; it lands under dispatch_allowed, never as a violation."""
    lk = threading.Lock()
    # fake the creation site to the allowlisted module
    sanitizer._LOCK_SITES[lk._san_id] = \
        "/x/kyverno_tpu/lifecycle/manager.py:162 in __init__"
    with lk:
        sanitizer.note_device_dispatch()
    rep = sanitizer.report()
    assert rep["dispatch_violations"] == []
    assert len(rep["dispatch_allowed"]) == 1


def test_env_knob_end_to_end(tmp_path):
    """KYVERNO_TPU_SANITIZE=1 in a fresh process: package import arms
    the wrappers, the atexit hook writes the JSON report, and a seeded
    inversion inside engine-shaped code shows up in it."""
    report = tmp_path / "san.json"
    code = """
import threading
import kyverno_tpu  # arms the sanitizer via the env knob

from kyverno_tpu.devtools import sanitizer
assert sanitizer.ENABLED
a, b = threading.Lock(), threading.Lock()

def t1():
    with a:
        with b:
            pass

def t2():
    with b:
        with a:
            pass

for fn in (t1, t2):
    t = threading.Thread(target=fn)
    t.start()
    t.join()
"""
    env = dict(os.environ, KYVERNO_TPU_SANITIZE="1",
               KYVERNO_TPU_SANITIZE_REPORT=str(report),
               JAX_PLATFORMS="cpu")
    proc = subprocess.run([sys.executable, "-c", code], env=env,
                          capture_output=True, text=True, timeout=120,
                          cwd=REPO)
    assert proc.returncode == 0, proc.stderr
    assert "LOCK-ORDER VIOLATIONS" in proc.stderr
    doc = json.loads(report.read_text())
    assert len(doc["cycles"]) == 1
    assert doc["locks_tracked"] >= 2


def test_sanitized_smoke_admission_pipeline(sanitized):
    """Tier-1-speed smoke: a real AdmissionPipeline (queue cv, stats
    lock, resolver events) under the sanitizer — no crashes, no
    cycles. The full chaos suites run under scripts_lint_gate.sh."""
    from kyverno_tpu.serving.batcher import AdmissionPipeline, BatchConfig

    calls = []

    def evaluate(payloads, version=None):
        calls.append(len(payloads))
        return [{"n": p} for p in payloads]

    p = AdmissionPipeline(evaluate, config=BatchConfig(
        max_batch_size=8, max_wait_ms=2.0, deadline_ms=2000.0))
    try:
        threads = [threading.Thread(
            target=lambda i=i: [p.submit(i) for _ in range(5)])
            for i in range(6)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=30)
    finally:
        p.stop()
    rep = sanitizer.report()
    assert rep["cycles"] == [], rep["cycles"]
    assert rep["locks_tracked"] > 0
