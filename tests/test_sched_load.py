"""Mixed-traffic overload test (slow tier): a bulk flood plus a
latency-critical trickle through the real batching Handlers, with
tpu.dispatch faults armed at p=0.3 and 100% shadow verification.

The overload contract under chaos:
- every critical request gets a correct verdict (matches the scalar
  oracle) and none of them are shed or expired;
- shedding hits the BULK class first (and only it, at these sizes);
- zero verdict divergence across shed, hedged, and batched paths —
  the shadow verifier is the referee.
"""

import concurrent.futures
import threading
import time

import numpy as np
import pytest

from kyverno_tpu.serving import BatchConfig, ClassifyConfig
from tests.test_serving import DEVICE_POLICY, HOST_POLICY, _pod

pytestmark = pytest.mark.slow

N_BULK_THREADS = 24
BULK_PER_THREAD = 16
N_CRIT = 60


@pytest.fixture(autouse=True)
def _clean_faults_and_breaker():
    # the TPU breaker is process-wide: 30% dispatch faults trip it
    # OPEN, and without a reset every later test in the process would
    # silently run on the scalar-fallback path
    from kyverno_tpu.resilience import global_faults, tpu_breaker

    global_faults.disarm()
    tpu_breaker().reset()
    yield
    global_faults.disarm()
    tpu_breaker().reset()


def _review(resource, uid, username):
    return {"apiVersion": "admission.k8s.io/v1", "kind": "AdmissionReview",
            "request": {"uid": uid, "operation": "CREATE",
                        "namespace": "default", "object": resource,
                        "userInfo": {"username": username}}}


def _mk_batched_handlers():
    from kyverno_tpu.api.policy import ClusterPolicy
    from kyverno_tpu.cluster import PolicyCache
    from kyverno_tpu.webhooks import build_handlers

    cache = PolicyCache()
    cache.set(ClusterPolicy.from_dict(DEVICE_POLICY))
    cache.set(ClusterPolicy.from_dict(HOST_POLICY))
    return build_handlers(
        cache, batching=True,
        batch_config=BatchConfig(
            max_batch_size=16, max_wait_ms=5.0, min_bucket=16,
            high_water=24, bulk_share=0.5, critical_reserve=0.1,
            bulk_max_wait_ms=40.0, hedge_threshold=0.25,
            bulk_shed_mode="fail",
            # burn thresholds off: this test pins the shed cause to the
            # class queue share so the bulk-first assertion is exact
            shed_burn_bulk=0.0, shed_burn_default=0.0),
        classify_config=ClassifyConfig(critical_users=("alice*",)))


def test_mixed_traffic_critical_protected_under_dispatch_faults(
        no_verdict_cache):
    from kyverno_tpu.observability.flightrecorder import global_flight
    from kyverno_tpu.observability.verification import global_verifier
    from kyverno_tpu.resilience.faults import global_faults

    global_flight.configure(sample_rate=1.0)
    global_verifier.configure(rate=1.0)
    handlers = _mk_batched_handlers()
    # warm the jit cache before arming chaos so the flood measures
    # scheduling, not compilation
    warm = handlers.validate(_review(_pod("warm", False), "w0", "alice"))
    assert warm["response"]["allowed"] is True

    global_faults.arm("tpu.dispatch", mode="raise", p=0.3, seed=7)
    crit_results = {}
    crit_lat = []
    crit_lock = threading.Lock()
    stop_flood = threading.Event()

    def bulk_worker(tid):
        # kubelet-storm shape: classified bulk via the username glob
        for i in range(BULK_PER_THREAD):
            if stop_flood.is_set():
                return
            handlers.validate(_review(
                _pod(f"bulk-{tid}-{i}", i % 2 == 0), f"b{tid}-{i}",
                f"system:node:worker-{tid}"))

    def crit_worker():
        # latency-critical trickle: paced user applies
        for i in range(N_CRIT):
            r = _review(_pod(f"crit-{i}", i % 2 == 0), f"c{i}", "alice")
            t0 = time.perf_counter()
            out = handlers.validate(r)
            dt = time.perf_counter() - t0
            with crit_lock:
                crit_results[f"c{i}"] = (r, out)
                crit_lat.append(dt)
            time.sleep(0.005)

    try:
        with concurrent.futures.ThreadPoolExecutor(
                max_workers=N_BULK_THREADS + 1) as ex:
            flood = [ex.submit(bulk_worker, t)
                     for t in range(N_BULK_THREADS)]
            crit = ex.submit(crit_worker)
            crit.result(timeout=300)
            stop_flood.set()
            for f in flood:
                f.result(timeout=300)
    finally:
        stop_flood.set()
        global_faults.disarm("tpu.dispatch")
    stats = handlers.pipeline.state()["stats"]
    handlers.pipeline.stop()
    handlers.batcher.stop()

    # every critical request decided, correctly (vs the scalar oracle),
    # and the critical class was never shed or expired
    from tests.test_serving import _mk_handlers

    scalar = _mk_handlers(batching=False, engine="scalar")
    for uid, (r, got) in crit_results.items():
        want = scalar.validate(r)
        assert got["response"]["allowed"] == want["response"]["allowed"], uid
        assert "evaluation error" not in str(
            got["response"].get("status", "")), uid
    scalar.batcher.stop()
    assert len(crit_results) == N_CRIT
    by_class = stats["by_class"]
    assert by_class.get("critical", {}).get("shed", 0) == 0
    assert by_class.get("critical", {}).get("expired", 0) == 0
    # overload landed on the bulk class first — and at these sizes,
    # only on it
    assert by_class.get("bulk", {}).get("shed", 0) > 0, by_class
    assert by_class.get("default", {}).get("shed", 0) == 0

    # critical p99 stays inside the flush envelope — the flood and the
    # injected dispatch faults never starved the trickle into its
    # deadline (the webhook budget is 10s; "flat" here means orders of
    # magnitude under it)
    p99 = float(np.percentile(np.asarray(crit_lat), 99))
    assert p99 < 2.0, f"critical p99 {p99:.3f}s"

    # zero verdict divergence across every path the chaos run exercised
    global_verifier.drain(timeout=60.0)
    vstats = global_verifier.state()["stats"]
    assert vstats.get("checked", 0) > 0
    assert vstats.get("divergences", 0) == 0
    flight_outcomes = global_flight.state()["stats"]["by_outcome"]
    # the fault storm forced fallbacks into the ring (always-capture)
    assert flight_outcomes.get("fallback", 0) + \
        flight_outcomes.get("shed", 0) > 0
