"""ControlPlane (`serve`): admission + snapshot + scan + metrics
round-trip over HTTP."""

import http.client
import json

import pytest

from kyverno_tpu.api.policy import ClusterPolicy
from kyverno_tpu.cli.serve import ControlPlane

POLICY = ClusterPolicy.from_dict({
    "apiVersion": "kyverno.io/v1", "kind": "ClusterPolicy",
    "metadata": {"name": "no-privileged"},
    "spec": {
        "validationFailureAction": "Enforce",
        "rules": [{
            "name": "privileged",
            "match": {"any": [{"resources": {"kinds": ["Pod"]}}]},
            "validate": {
                "message": "privileged is forbidden",
                "pattern": {"spec": {"containers": [
                    {"=(securityContext)": {"=(privileged)": "false"}}]}},
            },
        }],
    },
})


def pod(name, priv):
    return {"apiVersion": "v1", "kind": "Pod",
            "metadata": {"name": name, "namespace": "default"},
            "spec": {"containers": [{
                "name": "c", "image": "nginx",
                "securityContext": {"privileged": priv}}]}}


@pytest.fixture(scope="module")
def cp():
    plane = ControlPlane([POLICY], port=0, metrics_port=0)
    plane.start(scan_interval=3600)  # scans driven explicitly below
    yield plane
    plane.stop()


def _req(port, method, path, body=None):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=60)
    conn.request(method, path, json.dumps(body) if body is not None else None,
                 {"Content-Type": "application/json"})
    resp = conn.getresponse()
    data = resp.read()
    conn.close()
    return resp.status, data


def test_snapshot_scan_reports_metrics(cp):
    mport = cp.metrics_server.server_address[1]
    for i, priv in enumerate([True, False, False]):
        status, _ = _req(mport, "POST", "/snapshot/upsert", pod(f"p{i}", priv))
        assert status == 200
    status, data = _req(mport, "POST", "/scan", {})
    out = json.loads(data)
    assert status == 200 and out["scanned"] == 3
    assert out["summary"]["fail"] == 1 and out["summary"]["pass"] == 2
    status, data = _req(mport, "GET", "/reports")
    reports = json.loads(data)
    assert reports["default"]["summary"]["fail"] == 1
    status, data = _req(mport, "GET", "/metrics")
    assert status == 200 and b"# TYPE" in data


def test_admission_alongside_scan(cp):
    review = {"apiVersion": "admission.k8s.io/v1", "kind": "AdmissionReview",
              "request": {"uid": "u", "operation": "CREATE",
                          "namespace": "default", "object": pod("adm", True)}}
    status, data = _req(cp.admission.port, "POST", "/validate", review)
    out = json.loads(data)
    assert status == 200 and out["response"]["allowed"] is False
