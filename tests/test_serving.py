"""Serving pipeline: queue/batcher edge cases (fake evaluator, no
device) + a fast CPU batching smoke test proving batched verdicts match
the scalar oracle through the real Handlers."""

import concurrent.futures
import threading
import time

import pytest

from kyverno_tpu.serving import (AdmissionPipeline, BatchConfig,
                                 AdmissionQueue, DeadlineExceededError,
                                 QueueFullError)

# ---------------------------------------------------------------------------
# queue


def test_queue_fifo_and_high_water():
    q = AdmissionQueue(high_water=3)
    reqs = [q.put(i, deadline=time.monotonic() + 10) for i in range(3)]
    with pytest.raises(QueueFullError):
        q.put(99, deadline=time.monotonic() + 10)
    with q.cv:
        batch = q.drain(2)
    assert [r.payload for r in batch] == [0, 1]
    assert q.depth() == 1 and q.oldest() is reqs[2]


def test_queue_put_after_close_fails_fast():
    q = AdmissionQueue()
    with q.cv:
        q.closed = True
    with pytest.raises(RuntimeError, match="closed"):
        q.put(1, deadline=time.monotonic() + 10)


# ---------------------------------------------------------------------------
# pipeline edge cases (fake evaluate_fn — the contract is: payloads
# arrive padded with None to the shape bucket, results cover the real
# leading prefix)


def _echo_evaluate(calls=None):
    def fn(payloads):
        if calls is not None:
            calls.append(list(payloads))
        return [("ok", p) for p in payloads if p is not None]
    return fn


def test_single_request_light_load_pads_to_min_bucket():
    calls = []
    p = AdmissionPipeline(_echo_evaluate(calls),
                          config=BatchConfig(max_batch_size=8, max_wait_ms=1.0,
                                             min_bucket=16))
    assert p.submit("r1") == ("ok", "r1")
    p.stop()
    assert len(calls) == 1
    assert len(calls[0]) == 16 and calls[0][0] == "r1"
    assert calls[0][1:] == [None] * 15  # padded, not recompiled-for-1
    assert p.stats["flushes_by_bucket"] == {16: 1}
    assert p.stats["flush_reasons"].get("timer", 0) == 1


def test_size_flush_at_max_batch():
    calls = []
    ev = threading.Event()

    def gated(payloads):
        ev.wait(5)
        return _echo_evaluate(calls)(payloads)

    p = AdmissionPipeline(gated, config=BatchConfig(
        max_batch_size=4, max_wait_ms=5000.0, min_bucket=4))
    with concurrent.futures.ThreadPoolExecutor(max_workers=4) as ex:
        futs = [ex.submit(p.submit, f"r{i}") for i in range(4)]
        ev.set()
        outs = [f.result(timeout=10) for f in futs]
    p.stop()
    assert sorted(o[1] for o in outs) == ["r0", "r1", "r2", "r3"]
    assert p.stats["flush_reasons"].get("size", 0) >= 1


def test_empty_flush_on_shutdown_is_noop():
    p = AdmissionPipeline(_echo_evaluate())
    p.stop()
    assert p.stats["flushes"] == 0 and p.stats["requests"] == 0
    assert not p._flusher.is_alive()
    with pytest.raises(RuntimeError):
        p.submit("late")


def test_shutdown_flushes_queued_requests():
    # a request sitting under a long flush timer still completes when
    # stop() triggers the final shutdown drain
    p = AdmissionPipeline(_echo_evaluate(), config=BatchConfig(
        max_batch_size=64, max_wait_ms=60_000.0))
    with concurrent.futures.ThreadPoolExecutor(max_workers=1) as ex:
        fut = ex.submit(p.submit, "r1")
        time.sleep(0.05)  # let it enqueue (flusher now sleeping on timer)
        p.stop()
        assert fut.result(timeout=10) == ("ok", "r1")
    assert p.stats["flush_reasons"].get("shutdown", 0) == 1


def test_deadline_expiry_mid_queue():
    started = threading.Event()
    release = threading.Event()

    def slow(payloads):
        started.set()
        release.wait(10)
        return [("ok", p) for p in payloads if p is not None]

    p = AdmissionPipeline(slow, config=BatchConfig(
        max_batch_size=1, max_wait_ms=1.0, min_bucket=1))
    with concurrent.futures.ThreadPoolExecutor(max_workers=2) as ex:
        f1 = ex.submit(p.submit, "r1")
        assert started.wait(5)  # r1's batch is on the (blocked) device
        f2 = ex.submit(p.submit, "r2", 20.0)  # 20 ms budget, queued
        time.sleep(0.1)  # r2's deadline expires while waiting in queue
        release.set()
        assert f1.result(timeout=10) == ("ok", "r1")
        with pytest.raises(DeadlineExceededError):
            f2.result(timeout=10)
    p.stop()
    assert p.stats["expired"] == 1


def test_deadline_shorter_than_timer_still_evaluates():
    """A deadline tighter than max_wait_ms must trigger an EARLY flush
    that evaluates the request — not drain it already expired (the
    flush leads the deadline by deadline_lead_ms)."""
    p = AdmissionPipeline(
        lambda payloads: [("ok", x) for x in payloads if x is not None],
        config=BatchConfig(max_batch_size=8, max_wait_ms=500.0,
                           min_bucket=1, deadline_lead_ms=20.0))
    t0 = time.monotonic()
    assert p.submit("r", deadline_ms=100.0) == ("ok", "r")
    assert time.monotonic() - t0 < 0.5  # deadline flush, not the timer
    p.stop()
    assert p.stats["expired"] == 0
    assert p.stats["flush_reasons"] == {"deadline": 1}


def test_queue_full_sheds_to_fallback_scalar():
    started = threading.Event()
    release = threading.Event()

    def slow(payloads):
        started.set()
        release.wait(10)
        return [("batched", p) for p in payloads if p is not None]

    p = AdmissionPipeline(
        slow, scalar_fallback=lambda payload: ("scalar", payload),
        config=BatchConfig(max_batch_size=1, max_wait_ms=1.0, min_bucket=1,
                           high_water=1, shed_mode="scalar"))
    with concurrent.futures.ThreadPoolExecutor(max_workers=2) as ex:
        f1 = ex.submit(p.submit, "r1")
        assert started.wait(5)
        f2 = ex.submit(p.submit, "r2")  # fills the queue to high-water
        time.sleep(0.05)
        assert p.submit("r3") == ("scalar", "r3")  # shed, degraded inline
        release.set()
        assert f1.result(timeout=10) == ("batched", "r1")
        assert f2.result(timeout=10) == ("batched", "r2")
    p.stop()
    assert p.stats["shed"] == 1


def test_queue_full_shed_mode_fail_raises():
    started = threading.Event()
    release = threading.Event()

    def slow(payloads):
        started.set()
        release.wait(10)
        return [("batched", p) for p in payloads if p is not None]

    p = AdmissionPipeline(slow, config=BatchConfig(
        max_batch_size=1, max_wait_ms=1.0, min_bucket=1,
        high_water=1, shed_mode="fail"))
    with concurrent.futures.ThreadPoolExecutor(max_workers=2) as ex:
        f1 = ex.submit(p.submit, "r1")
        assert started.wait(5)
        f2 = ex.submit(p.submit, "r2")
        time.sleep(0.05)
        with pytest.raises(QueueFullError):
            p.submit("r3")
        release.set()
        f1.result(timeout=10)
        f2.result(timeout=10)
    p.stop()


def test_evaluator_error_propagates_to_all_waiters():
    def boom(payloads):
        raise ValueError("device fell over")

    p = AdmissionPipeline(boom, config=BatchConfig(
        max_batch_size=2, max_wait_ms=1.0, min_bucket=2))
    with concurrent.futures.ThreadPoolExecutor(max_workers=2) as ex:
        futs = [ex.submit(p.submit, f"r{i}") for i in range(2)]
        for f in futs:
            with pytest.raises(ValueError, match="device fell over"):
                f.result(timeout=10)
    p.stop()


def test_submit_eval_grace_clamped_to_caller_budget():
    # a dispatched request whose evaluator wedges must resolve inside
    # the caller's wall (queue budget + eval_grace_s), not the default
    # 30s grace — the API server hung up long before that
    release = threading.Event()

    def wedged(payloads):
        release.wait(10.0)
        return ["late"] * len(payloads)

    p = AdmissionPipeline(wedged, config=BatchConfig(
        max_batch_size=1, max_wait_ms=1.0, min_bucket=1))
    t0 = time.monotonic()
    with pytest.raises(DeadlineExceededError, match="evaluation timed out"):
        p.submit("r0", deadline_ms=200.0, eval_grace_s=0.2)
    assert time.monotonic() - t0 < 2.0
    release.set()
    p.stop()


def test_bucket_shapes_are_powers_of_two():
    cfg = BatchConfig(min_bucket=16, max_batch_size=100)
    assert [cfg.bucket(n) for n in (1, 16, 17, 33, 100)] == [16, 16, 32, 64, 128]


# ---------------------------------------------------------------------------
# CPU batching smoke: real Handlers, batched verdicts == scalar oracle,
# including a mixed device/host-fallback batch


DEVICE_POLICY = {
    "apiVersion": "kyverno.io/v1", "kind": "ClusterPolicy",
    "metadata": {"name": "no-privileged"},
    "spec": {
        "validationFailureAction": "Enforce",
        "rules": [{
            "name": "privileged",
            "match": {"any": [{"resources": {"kinds": ["Pod"]}}]},
            "validate": {
                "message": "privileged is forbidden",
                "pattern": {"spec": {"containers": [
                    {"=(securityContext)": {"=(privileged)": "false"}}]}},
            },
        }],
    },
}

# deprecated `In` operator -> host-only rule (tpu/ir.py): resources it
# matches complete via the scalar engine INSIDE the batch
HOST_POLICY = {
    "apiVersion": "kyverno.io/v1", "kind": "ClusterPolicy",
    "metadata": {"name": "host-only-cm"},
    "spec": {
        "validationFailureAction": "Enforce",
        "rules": [{
            "name": "cm-keys",
            "match": {"any": [{"resources": {"kinds": ["ConfigMap"]}}]},
            "validate": {"message": "forbidden key", "deny": {"conditions": {
                "any": [{"key": "forbidden", "operator": "In",
                         "value": "{{ request.object.data.keys(@) }}"}]}}},
        }],
    },
}


def _pod(name, priv):
    return {"apiVersion": "v1", "kind": "Pod",
            "metadata": {"name": name, "namespace": "default"},
            "spec": {"containers": [{
                "name": "c", "image": "nginx",
                "securityContext": {"privileged": priv}}]}}


def _cm(name, key):
    return {"apiVersion": "v1", "kind": "ConfigMap",
            "metadata": {"name": name, "namespace": "default"},
            "data": {key: "x"}}


def _review(resource, uid):
    return {"apiVersion": "admission.k8s.io/v1", "kind": "AdmissionReview",
            "request": {"uid": uid, "operation": "CREATE",
                        "namespace": "default", "object": resource}}


def _mk_handlers(batching, engine=None, **batch_kw):
    from kyverno_tpu.api.policy import ClusterPolicy
    from kyverno_tpu.cluster import PolicyCache
    from kyverno_tpu.config import Toggles
    from kyverno_tpu.webhooks import build_handlers

    cache = PolicyCache()
    cache.set(ClusterPolicy.from_dict(DEVICE_POLICY))
    cache.set(ClusterPolicy.from_dict(HOST_POLICY))
    kw = {}
    if batching:
        kw["batch_config"] = BatchConfig(**batch_kw) if batch_kw else None
    return build_handlers(cache, batching=batching,
                          toggles=Toggles(engine=engine) if engine else None,
                          **kw)


def test_batched_verdicts_match_scalar_mixed_host_fallback():
    resources = ([_pod(f"p{i}", i % 2 == 0) for i in range(6)]
                 + [_cm("cm-bad", "forbidden"), _cm("cm-ok", "a")])
    reviews = [_review(r, f"u{i}") for i, r in enumerate(resources)]

    batched = _mk_handlers(batching=True, max_batch_size=8, max_wait_ms=10.0)
    _, eng = batched._engine()
    dev, total = eng.cps.coverage()
    assert dev < total, "host-only rule must NOT lower to device"
    with concurrent.futures.ThreadPoolExecutor(max_workers=8) as ex:
        got = list(ex.map(batched.validate, reviews))
    batched.pipeline.stop()
    batched.batcher.stop()

    scalar = _mk_handlers(batching=False, engine="scalar")
    want = [scalar.validate(r) for r in reviews]
    scalar.batcher.stop()

    assert [g["response"]["allowed"] for g in got] \
        == [w["response"]["allowed"] for w in want]
    assert [g["response"].get("status") for g in got] \
        == [w["response"].get("status") for w in want]
    # the host-matched configmap really was decided inside a batch
    assert got[6]["response"]["allowed"] is False
    assert p_stats_requests(batched) == len(reviews)


def p_stats_requests(handlers):
    return handlers.pipeline.stats["requests"] + handlers.pipeline.stats["shed"]


def test_webhook_queue_budget_capped_by_configured_deadline_ms():
    # the queue budget handed to pipeline.submit must be the TIGHTER of
    # the request's remaining webhook budget and BatchConfig.deadline_ms
    # — otherwise `serve --batching --deadline-ms N` is dead config
    batched = _mk_handlers(batching=True, deadline_ms=100.0)
    seen = []
    orig = batched.pipeline.submit

    def spy(payload, deadline_ms=None, **kw):
        seen.append(deadline_ms)
        return orig(payload, deadline_ms=deadline_ms, **kw)

    batched.pipeline.submit = spy
    out = batched.validate(_review(_pod("p-cap", False), "u-cap"))
    assert out["response"]["allowed"] is True
    batched.pipeline.stop()
    batched.batcher.stop()
    # request_timeout_s defaults to 10s (10000ms): the 100ms config cap
    # must win
    assert seen and seen[0] == pytest.approx(100.0)


def test_serving_metrics_exposed_on_metrics_endpoint():
    import http.client
    import json as _json

    from kyverno_tpu.api.policy import ClusterPolicy
    from kyverno_tpu.cli.serve import ControlPlane

    cp = ControlPlane([ClusterPolicy.from_dict(DEVICE_POLICY)],
                      port=0, metrics_port=0, batching=True)
    cp.start(scan_interval=3600)
    try:
        conn = http.client.HTTPConnection(
            "127.0.0.1", cp.admission.port, timeout=60)
        conn.request("POST", "/validate", _json.dumps(_review(_pod("m", True), "u")),
                     {"Content-Type": "application/json"})
        out = _json.loads(conn.getresponse().read())
        conn.close()
        assert out["response"]["allowed"] is False
        mport = cp.metrics_server.server_address[1]
        conn = http.client.HTTPConnection("127.0.0.1", mport, timeout=60)
        conn.request("GET", "/metrics")
        body = conn.getresponse().read().decode()
        conn.close()
    finally:
        cp.stop()
    assert "kyverno_serving_queue_depth" in body
    assert 'kyverno_serving_flush_total{reason=' in body
    assert "kyverno_serving_batch_occupancy_bucket" in body
    assert "kyverno_serving_request_latency_seconds_count" in body
