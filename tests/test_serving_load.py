"""Serving pipeline load test (slow tier): 64 simultaneous admission
threads through the batching pipeline must produce verdicts identical
to the scalar path, with real batch amortization (mean batch size > 4)
and ZERO XLA recompiles after warmup — flushes within one shape bucket
reuse the compiled program."""

import concurrent.futures
import threading

import pytest

from kyverno_tpu.serving import BatchConfig
from tests.test_serving import (DEVICE_POLICY, HOST_POLICY, _cm, _mk_handlers,
                                _pod, _review)

pytestmark = pytest.mark.slow

N_THREADS = 64
REQUESTS_PER_THREAD = 3


def _requests():
    out = []
    for i in range(N_THREADS * REQUESTS_PER_THREAD):
        if i % 8 == 7:
            res = _cm(f"cm{i}", "forbidden" if i % 16 == 7 else "ok")
        else:
            res = _pod(f"p{i}", i % 2 == 0)
        out.append(_review(res, f"u{i}"))
    return out


def test_load_batched_equals_scalar_without_recompile(no_verdict_cache):
    # cache off: this test measures COALESCING (mean batch size, jit
    # cache stability) — the verdict cache would legitimately answer
    # repeat reviews at submit() and starve the queue it is probing
    from kyverno_tpu.webhooks.server import _payload_from_request

    batched = _mk_handlers(batching=True, max_batch_size=32, max_wait_ms=20.0)
    reviews = _requests()

    # warmup: dispatch once at every bucket the pipeline can produce
    # (16 and 32) so the measured phase runs against a warm jit cache
    _, eng = batched._engine()
    payload = _payload_from_request(reviews[0]["request"])
    for bucket in (16, 32):
        batched._evaluate_padded([payload] + [None] * (bucket - 1))
    fn = eng.cps.device_fn()
    if not hasattr(fn, "_cache_size"):
        pytest.skip("jax jit cache introspection unavailable")
    compiles_after_warmup = fn._cache_size()
    assert compiles_after_warmup <= 2

    barrier = threading.Barrier(N_THREADS)
    results = {}
    res_lock = threading.Lock()

    def worker(tid):
        barrier.wait()  # all 64 threads hit the pipeline simultaneously
        local = {}
        for r in reviews[tid::N_THREADS]:
            local[r["request"]["uid"]] = batched.validate(r)
        with res_lock:
            results.update(local)

    with concurrent.futures.ThreadPoolExecutor(max_workers=N_THREADS) as ex:
        list(ex.map(worker, range(N_THREADS)))
    stats = dict(batched.pipeline.stats)
    mean_batch = batched.pipeline.mean_batch_size()
    compiles_after_load = fn._cache_size()
    batched.pipeline.stop()
    batched.batcher.stop()

    scalar = _mk_handlers(batching=False, engine="scalar")
    want = {r["request"]["uid"]: scalar.validate(r) for r in reviews}
    scalar.batcher.stop()

    assert len(results) == len(reviews)
    for uid, got in results.items():
        assert got["response"]["allowed"] == want[uid]["response"]["allowed"], uid
        assert got["response"].get("status") == want[uid]["response"].get("status"), uid

    # real coalescing happened, and shape bucketing kept the jit cache
    # frozen: repeated flushes within a bucket never recompiled
    assert stats["shed"] == 0 and stats["expired"] == 0
    assert mean_batch > 4, stats
    assert sum(stats["flushes_by_bucket"].values()) >= 2
    assert compiles_after_load == compiles_after_warmup, stats
