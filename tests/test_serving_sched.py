"""Admission scheduling: class extraction, weighted-fair queuing,
deadline-aware flush composition, bulk coalescing, burn-driven
shedding, hedged scalar dispatch, and priority-ordered shutdown drain
(fake evaluators — no device)."""

import concurrent.futures
import threading
import time

import pytest

from kyverno_tpu.serving import (AdmissionPipeline, AdmissionQueue,
                                 BatchConfig, ClassifyConfig, QueueFullError,
                                 RequestClass, classify_request,
                                 parse_class_weights)

CRIT = RequestClass("t1", "CREATE", "critical")
DFLT = RequestClass("t1", "CREATE", "default")
BULK = RequestClass("t1", "CREATE", "bulk")


def far(seconds=60.0):
    return time.monotonic() + seconds


# ---------------------------------------------------------------------------
# class extraction (serving/scheduler.py)


def test_classify_defaults():
    cfg = ClassifyConfig()
    assert classify_request(cfg, operation="CREATE", username="alice",
                            namespace="apps").priority == "default"
    assert classify_request(cfg, username="system:node:worker-1",
                            namespace="ns").priority == "bulk"
    assert classify_request(
        cfg, username="system:serviceaccount:kube-system:gc",
    ).priority == "bulk"
    assert classify_request(cfg, username="alice",
                            dry_run=True).priority == "bulk"
    assert classify_request(cfg, username="alice",
                            groups=["system:nodes"]).priority == "bulk"


def test_classify_annotation_and_user_globs():
    cfg = ClassifyConfig(critical_users=("deploy-bot*",))
    assert classify_request(cfg, username="deploy-bot-7").priority == "critical"
    res_crit = {"metadata": {"annotations":
                             {"policies.kyverno.io/priority": "critical"}}}
    # the annotation is requester-controlled: a self-stamped "critical"
    # must NOT promote past the overload ladder by default...
    assert classify_request(cfg, username="system:node:n1",
                            resource=res_crit).priority == "bulk"
    assert classify_request(cfg, username="alice",
                            resource=res_crit).priority == "default"
    # ...unless the operator opted in
    trusting = ClassifyConfig(trust_annotation_critical=True)
    assert classify_request(trusting, username="alice",
                            resource=res_crit).priority == "critical"
    # self-DEMOTION is always honored (harming yourself is allowed)...
    res_bulk = {"metadata": {"annotations":
                             {"policies.kyverno.io/priority": "bulk"}}}
    assert classify_request(cfg, username="alice",
                            resource=res_bulk).priority == "bulk"
    # ...but never of a --critical-users identity: the annotation lives
    # on the OBJECT (authored by whoever last wrote it), so honoring it
    # against trusted identity would let anyone who can annotate demote
    # someone else's critical traffic into the shed ladder
    assert classify_request(cfg, username="deploy-bot-7",
                            resource=res_bulk).priority == "critical"
    # unknown annotation values are ignored, not trusted
    res_bad = {"metadata": {"annotations":
                            {"policies.kyverno.io/priority": "turbo"}}}
    assert classify_request(cfg, username="alice",
                            resource=res_bad).priority == "default"
    # tenant falls back username -> _cluster for cluster-scoped requests
    assert classify_request(cfg, username="alice").tenant == "alice"
    assert classify_request(cfg).tenant == "_cluster"


def test_parse_class_weights():
    w = parse_class_weights("bulk=2,critical=16")
    assert w["bulk"] == 2.0 and w["critical"] == 16.0 and w["default"] == 4.0
    with pytest.raises(ValueError):
        parse_class_weights("turbo=1")
    with pytest.raises(ValueError):
        parse_class_weights("bulk=0")


# ---------------------------------------------------------------------------
# weighted-fair drain composition (serving/queue.py)


def _sched_cfg(**kw):
    kw.setdefault("min_bucket", 1)
    kw.setdefault("max_wait_ms", 2.0)
    return BatchConfig(**kw)


def test_wfq_default_outranks_backlogged_bulk():
    cfg = _sched_cfg()
    q = AdmissionQueue(high_water=100, config=cfg)
    bulk = [q.put(f"b{i}", far(), cls=BULK) for i in range(2)]
    dflt = [q.put(f"d{i}", far(), cls=DFLT) for i in range(3)]
    with q.cv:
        batch = q.drain(4, config=cfg)
    # defaults (weight 4) drain ahead of the earlier-arrived bulk
    # backlog; the 4th slot is a bulk top-up to the shape bucket —
    # a free rider on a slot that would have been padding
    assert [r.payload for r in batch] == ["d0", "d1", "d2", "b0"]
    assert q.last_drain_info["bulk_topup"] == 1
    assert bulk[1].dispatched is False and dflt[0].dispatched is True


def test_wfq_interleaves_tenants_within_tier():
    cfg = _sched_cfg(min_bucket=16)
    q = AdmissionQueue(high_water=100, config=cfg)
    a = RequestClass("tenant-a", "CREATE", "default")
    b = RequestClass("tenant-b", "CREATE", "default")
    for i in range(3):
        q.put(f"a{i}", far(), cls=a)
    for i in range(3):
        q.put(f"b{i}", far(), cls=b)
    with q.cv:
        batch = q.drain(6, config=cfg)
    # equal-weight flows interleave by virtual finish time instead of
    # strict arrival order (tenant-a would otherwise starve tenant-b)
    assert [r.payload for r in batch] == ["a0", "b0", "a1", "b1", "a2", "b2"]


def test_urgent_deadline_rides_next_flush_regardless_of_class():
    cfg = _sched_cfg(urgent_ms=50.0, bulk_max_wait_ms=60_000.0)
    q = AdmissionQueue(high_water=100, config=cfg)
    q.put("d0", far(), cls=DFLT)
    urgent_bulk = q.put("b-urgent", time.monotonic() + 0.02, cls=BULK)
    q.put("b-later", far(), cls=BULK)
    with q.cv:
        batch = q.drain(2, config=cfg)
    # the deadline-imminent bulk entry rides FIRST even though bulk is
    # young and its coalescing timer is an hour out
    assert batch[0] is urgent_bulk
    assert [r.payload for r in batch] == ["b-urgent", "d0"]
    assert q.last_drain_info["urgent"] == 1


def test_bulk_coalesces_until_mature_or_full():
    cfg = _sched_cfg(min_bucket=1, bulk_max_wait_ms=60_000.0)
    q = AdmissionQueue(high_water=100, config=cfg)
    for i in range(3):
        q.put(f"b{i}", far(), cls=BULK)
    with q.cv:
        batch = q.drain(8, config=cfg)
    # nothing else queued and the window has not matured: bulk holds
    assert batch == [] and q.depth() == 3
    # a full batch of bulk is mature by size
    for i in range(3, 8):
        q.put(f"b{i}", far(), cls=BULK)
    with q.cv:
        batch = q.drain(8, config=cfg)
    assert len(batch) == 8 and q.last_drain_info["bulk_mature"] is True


def test_pipeline_bulk_flushes_on_its_own_window():
    done = []
    p = AdmissionPipeline(
        lambda payloads: [("ok", x) for x in payloads if x is not None],
        config=_sched_cfg(max_batch_size=8, max_wait_ms=2.0,
                          bulk_max_wait_ms=150.0))
    try:
        t0 = time.monotonic()
        out = p.submit("b1", cls=BULK)
        dt_bulk = time.monotonic() - t0
        assert out == ("ok", "b1")
        t0 = time.monotonic()
        p.submit("d1", cls=DFLT)
        dt_dflt = time.monotonic() - t0
    finally:
        p.stop()
    # bulk coalesced for its own (longer) window; default rode the
    # 2ms timer
    assert dt_bulk >= 0.1, dt_bulk
    assert dt_dflt < 0.1, dt_dflt
    assert p.stats["flush_reasons"].get("bulk_timer", 0) == 1
    assert p.stats["by_class"]["bulk"]["evaluated"] == 1
    assert p.stats["by_class"]["default"]["evaluated"] == 1


# ---------------------------------------------------------------------------
# burn-driven shed ladder + class queue shares


def test_burn_shed_bulk_first_default_later_critical_never():
    burn = {"v": 0.0}
    calls = []

    def scalar(payload):
        calls.append(payload)
        return ("scalar", payload)

    p = AdmissionPipeline(
        lambda payloads: [("ok", x) for x in payloads if x is not None],
        scalar_fallback=scalar,
        config=_sched_cfg(max_batch_size=4, shed_burn_bulk=1.0,
                          shed_burn_default=3.0),
        burn_provider=lambda: burn["v"])
    try:
        burn["v"] = 2.0  # past the bulk rung, under the default rung
        assert p.submit("b1", cls=BULK) == ("scalar", "b1")
        assert p.submit("d1", cls=DFLT) == ("ok", "d1")
        burn["v"] = 5.0  # past the default rung too
        assert p.submit("d2", cls=DFLT) == ("scalar", "d2")
        assert p.submit("c1", cls=CRIT) == ("ok", "c1")  # never burn-shed
    finally:
        p.stop()
    assert p.stats["by_class"]["bulk"]["shed"] == 1
    assert p.stats["by_class"]["default"]["shed"] == 1
    assert p.stats["by_class"].get("critical", {}).get("shed", 0) == 0
    assert calls == ["b1", "d2"]


def test_bulk_shed_mode_fail_overrides_global_scalar():
    p = AdmissionPipeline(
        lambda payloads: [("ok", x) for x in payloads if x is not None],
        scalar_fallback=lambda payload: ("scalar", payload),
        config=_sched_cfg(shed_mode="scalar", bulk_shed_mode="fail",
                          shed_burn_bulk=1.0),
        burn_provider=lambda: 9.0)
    try:
        with pytest.raises(QueueFullError, match="class=bulk"):
            p.submit("b1", cls=BULK)
    finally:
        p.stop()


def test_bulk_queue_share_sheds_bulk_while_default_enqueues():
    started = threading.Event()
    release = threading.Event()

    def gated(payloads):
        started.set()
        release.wait(10)
        return [("ok", x) for x in payloads if x is not None]

    cfg = _sched_cfg(max_batch_size=1, high_water=10, bulk_share=0.2,
                     critical_reserve=0.0, bulk_max_wait_ms=60_000.0,
                     bulk_shed_mode="fail")
    p = AdmissionPipeline(gated, config=cfg)
    with concurrent.futures.ThreadPoolExecutor(max_workers=8) as ex:
        f0 = ex.submit(p.submit, "d0", None, None, DFLT)
        assert started.wait(5)  # flusher busy; queue now accumulates
        futs = [ex.submit(p.submit, f"b{i}", None, None, BULK)
                for i in range(2)]
        time.sleep(0.05)
        assert p.queue.depth_by_class().get("bulk") == 2
        with pytest.raises(QueueFullError, match="queue share"):
            p.submit("b-over", cls=BULK)  # bulk capped at 0.2 * 10 = 2
        f_d = ex.submit(p.submit, "d1", None, None, DFLT)  # default fine
        time.sleep(0.05)
        release.set()
        assert f0.result(10) == ("ok", "d0")
        assert f_d.result(10) == ("ok", "d1")
        for f in futs:
            assert f.result(10)[0] == "ok"
    p.stop()
    assert p.stats["by_class"]["bulk"]["shed"] == 1


# ---------------------------------------------------------------------------
# shutdown drains priority order (satellite regression)


def test_stop_drains_critical_before_bulk():
    wedged = threading.Event()
    release = threading.Event()

    def stuck(payloads):
        wedged.set()
        release.wait(30)
        return [("batched", x) for x in payloads if x is not None]

    order = []

    def scalar(payload):
        order.append(payload)
        return ("scalar", payload)

    p = AdmissionPipeline(
        stuck, scalar_fallback=scalar,
        config=_sched_cfg(max_batch_size=1, eval_grace_s=0.2,
                          bulk_max_wait_ms=60_000.0))
    results = {}
    threads = []

    def run(name, cls):
        results[name] = p.submit(name, 60_000, None, cls)

    threads.append(threading.Thread(target=run, args=("r0", DFLT)))
    threads[0].start()
    assert wedged.wait(5)  # r0 in flight on the stuck evaluator
    # queued strictly bulk-before-critical: the drain must invert it
    for name, cls in (("b1", BULK), ("b2", BULK), ("c1", CRIT),
                      ("d1", DFLT)):
        t = threading.Thread(target=run, args=(name, cls))
        t.start()
        threads.append(t)
        time.sleep(0.02)
    time.sleep(0.1)
    p.stop()  # join times out (0.2s); priority-ordered drain kicks in
    release.set()
    for t in threads:
        t.join(timeout=10)
        assert not t.is_alive()
    assert order == ["c1", "d1", "b1", "b2"]
    assert results["c1"] == ("scalar", "c1")
    assert results["r0"] == ("batched", "r0")
    assert p.queue.depth() == 0


# ---------------------------------------------------------------------------
# hedged scalar dispatch: both race orders, bit-identical, no double
# resolution, flight ring labels the winning path


def _flight_capture(records):
    def hook(payload, result, path, latency_s, trace_id, timings=None):
        records.append((payload, result, path))
    return hook


def test_hedge_scalar_wins_when_device_batch_stalls():
    release = threading.Event()

    def slow_eval(payloads):
        release.wait(5)
        return [("rows", x) for x in payloads if x is not None]

    records = []
    p = AdmissionPipeline(
        slow_eval,
        scalar_fallback=lambda payload: ("rows", payload),
        hedge_fn=lambda payload, version: ("rows", payload),
        config=_sched_cfg(max_batch_size=1, hedge_threshold=0.5),
        flight_hook=_flight_capture(records))
    try:
        t0 = time.monotonic()
        out = p.submit("r1", deadline_ms=600.0)
        dt = time.monotonic() - t0
    finally:
        release.set()
        p.stop()
    # the hedge resolved it (bit-identical rows) well before the
    # wedged device batch would have
    assert out == ("rows", "r1")
    assert dt < 0.6
    assert p.stats["hedges"] == 1
    assert p.stats["hedge_wins_scalar"] == 1
    assert p.stats["hedge_wins_device"] == 0
    # the flusher's late (discarded) resolution recorded the race with
    # the winning path labeled
    paths = [path for _, _, path in records]
    assert "hedged_scalar" in paths
    # the request resolved exactly once: the served result survived the
    # device batch's later resolve attempt
    assert ("r1", ("rows", "r1"), "hedged_scalar") in [
        (pl, res, path) for pl, res, path in records]


def test_hedge_device_wins_when_scalar_is_slow():
    # deadline 2s, threshold 0.9 -> the hedge fires ~0.2s in; the
    # device lands at ~0.3s while the slow oracle is still running
    def timed_eval(payloads):
        time.sleep(0.3)
        return [("rows", x) for x in payloads if x is not None]

    def slow_hedge(payload, version):
        time.sleep(0.6)
        return ("rows", payload)

    records = []
    p = AdmissionPipeline(
        timed_eval,
        scalar_fallback=lambda payload: ("rows", payload),
        hedge_fn=slow_hedge,
        config=_sched_cfg(max_batch_size=1, hedge_threshold=0.9),
        flight_hook=_flight_capture(records))
    try:
        out = p.submit("r1", deadline_ms=2000.0)
    finally:
        p.stop()
    assert out == ("rows", "r1")
    assert p.stats["hedges"] == 1
    assert p.stats["hedge_wins_device"] == 1
    assert p.stats["hedge_wins_scalar"] == 0
    # exactly ONE record for the hedged request — the losing hedge's
    # race record labeled with the winner; the flush suppresses its
    # own "batched" record so the ring (and the shadow verifier's
    # denominators) never count the request twice
    paths = [path for pl, _, path in records if pl == "r1"]
    assert paths == ["hedged_device"]


def test_hedge_fault_site_degrades_to_waiting():
    from kyverno_tpu.resilience.faults import global_faults

    def timed_eval(payloads):
        time.sleep(0.4)  # slow enough that the hedge point is reached
        return [("rows", x) for x in payloads if x is not None]

    global_faults.arm("serving.hedge", mode="raise")
    try:
        p = AdmissionPipeline(
            timed_eval, scalar_fallback=lambda payload: ("rows", payload),
            config=_sched_cfg(max_batch_size=1, hedge_threshold=0.9))
        try:
            out = p.submit("r1", deadline_ms=2000.0)
        finally:
            p.stop()
    finally:
        global_faults.disarm("serving.hedge")
    # the injected hedge failure cost nothing: the device batch
    # resolved the request normally
    assert out == ("rows", "r1")
    assert p.stats["hedges"] == 1
    assert p.stats["hedge_errors"] == 1
    assert p.stats["hedge_wins_scalar"] == 0


def test_slow_hedge_never_extends_wait_past_deadline():
    """Time spent inside the hedge race comes out of the request's own
    budget: a glacial oracle must not hold the caller for the full
    pre-hedge remainder ON TOP of the hedge duration."""
    from kyverno_tpu.serving import DeadlineExceededError

    release = threading.Event()

    def wedged(payloads):
        release.wait(10)
        return [("rows", x) for x in payloads if x is not None]

    def glacial_hedge(payload, version):
        time.sleep(2.0)  # overruns the 1s deadline all by itself
        raise RuntimeError("oracle fell over")

    p = AdmissionPipeline(
        wedged, scalar_fallback=lambda payload: ("rows", payload),
        hedge_fn=glacial_hedge,
        config=_sched_cfg(max_batch_size=1, hedge_threshold=0.9))
    t0 = time.monotonic()
    try:
        with pytest.raises(DeadlineExceededError):
            p.submit("r1", deadline_ms=1000.0, eval_grace_s=0.2)
        elapsed = time.monotonic() - t0
    finally:
        release.set()
        p.stop()
    # hedge point ~0.1s + 2.0s hedge + 0.2s grace ~= 2.3s; the old
    # fixed-remainder wait would add the untouched 0.9s budget on top
    assert elapsed < 2.7, elapsed
    assert p.stats["hedge_errors"] == 1


def test_hedged_outcome_always_captures():
    from kyverno_tpu.observability.flightrecorder import (ALWAYS_CAPTURE,
                                                          global_flight)

    assert "hedged" in ALWAYS_CAPTURE
    assert global_flight.classify(None, "hedged_scalar") == "hedged"
    assert global_flight.classify(None, "hedged_device") == "hedged"
    assert global_flight.classify(None, "hedged_device_error") == "hedged"


def test_hedge_lost_to_evaluator_error_counts_device_error():
    """The flush resolving with an evaluator ERROR before the oracle
    finishes is not a device win: the accounting and the flight record
    must say device_error, not a bit-identical race that never ran."""
    def failing_eval(payloads):
        time.sleep(0.25)  # past the hedge point, before the oracle ends
        raise RuntimeError("device batch failed")

    def slow_hedge(payload, version):
        time.sleep(0.6)
        return ("rows", payload)

    records = []
    p = AdmissionPipeline(
        failing_eval, scalar_fallback=lambda payload: ("rows", payload),
        hedge_fn=slow_hedge,
        config=_sched_cfg(max_batch_size=1, hedge_threshold=0.9),
        flight_hook=_flight_capture(records))
    try:
        with pytest.raises(RuntimeError):
            p.submit("r1", deadline_ms=2000.0)
    finally:
        p.stop()
    assert p.stats["hedges"] == 1
    assert p.stats["hedge_lost_to_error"] == 1
    assert p.stats["hedge_wins_device"] == 0
    assert p.stats["hedge_wins_scalar"] == 0
    # exactly one record, labeled with the truth and carrying the error
    entries = [(res, path) for pl, res, path in records if pl == "r1"]
    assert len(entries) == 1
    res, path = entries[0]
    assert path == "hedged_device_error"
    assert isinstance(res, RuntimeError)


def test_hedge_arms_after_late_dispatch():
    """The hedge condition is continuous: a request still QUEUED when
    its threshold trips (queue wait ate the budget — the overload case
    hedging exists for) must still race once the flush picks it up."""
    def slow_eval(payloads):
        time.sleep(0.5)
        return [("rows", x) for x in payloads if x is not None]

    p = AdmissionPipeline(
        slow_eval, scalar_fallback=lambda payload: ("rows", payload),
        hedge_fn=lambda payload, version: ("rows", payload),
        config=_sched_cfg(max_batch_size=1, hedge_threshold=0.7))
    results = {}
    try:
        t = threading.Thread(
            target=lambda: results.update(r1=p.submit("r1",
                                                      deadline_ms=3000.0)))
        t.start()
        time.sleep(0.1)  # r1's flush in flight; flusher busy ~0.5s
        # r2's hedge point (~0.24s in) arrives while it is still queued
        # behind r1's batch; it is dispatched at ~0.5s with ~0.4s budget
        # left against a 0.5s device batch — only a re-armed hedge wins
        t0 = time.monotonic()
        results["r2"] = p.submit("r2", deadline_ms=800.0)
        dt = time.monotonic() - t0
        t.join(timeout=10)
    finally:
        p.stop()
    assert results["r1"] == ("rows", "r1")
    assert results["r2"] == ("rows", "r2")
    assert dt < 0.8, dt
    assert p.stats["hedges"] == 1
    assert p.stats["hedge_wins_scalar"] == 1


def test_expired_drain_respects_prior_hedge_resolution():
    """A drained past-deadline request a hedge already resolved keeps
    the hedge's outcome — the flush must not also count it expired
    (one outcome per request)."""
    from kyverno_tpu.observability.metrics import MetricsRegistry

    reg = MetricsRegistry()
    p = AdmissionPipeline(
        lambda payloads: [("rows", x) for x in payloads if x is not None],
        config=_sched_cfg(max_batch_size=4), metrics=reg)
    try:
        now = time.monotonic()
        req = p.queue.put("r1", deadline=now - 1.0, now=now - 2.0, cls=DFLT)
        with p.queue.cv:
            batch = p.queue.drain(4, config=p.config)
        assert req in batch
        # a hedge race resolved it before _process ran
        assert req.resolve(("rows", "r1"), winner="hedge_scalar")
        p._process(batch, "timer")
    finally:
        p.stop()
    assert reg.serving_class_requests.value(
        {"class": "default", "outcome": "expired"}) == 0
    assert p.stats["expired"] == 0
    assert p.stats["by_class"]["default"]["expired"] == 0
    assert req.result == ("rows", "r1")


def test_parse_class_weights_rejects_nan_and_inf():
    with pytest.raises(ValueError):
        parse_class_weights("bulk=nan")
    with pytest.raises(ValueError):
        parse_class_weights("bulk=inf")
    # library-built dicts degrade to the default weight, never NaN tags
    from kyverno_tpu.serving.scheduler import class_weight

    assert class_weight({"bulk": float("nan")}, BULK) == 4.0
    assert class_weight({"bulk": float("inf")}, BULK) == 4.0


def test_critical_reserve_inert_without_critical_path():
    """With no promotion path to the critical tier configured, the
    reserve must not silently cut effective queue capacity."""
    from kyverno_tpu.api.policy import ClusterPolicy
    from kyverno_tpu.cluster import PolicyCache
    from kyverno_tpu.webhooks import build_handlers

    policy = {"apiVersion": "kyverno.io/v1", "kind": "ClusterPolicy",
              "metadata": {"name": "p"},
              "spec": {"rules": [{"name": "r",
                                  "match": {"any": [{"resources":
                                                     {"kinds": ["Pod"]}}]},
                                  "validate": {"message": "m",
                                               "pattern": {"metadata":
                                                           {"name": "?*"}}}}]}}
    cache = PolicyCache()
    cache.set(ClusterPolicy.from_dict(policy))
    # default classify config: nothing can ever classify critical
    h = build_handlers(cache, batching=True,
                       batch_config=BatchConfig(critical_reserve=0.1))
    try:
        assert h.pipeline.config.critical_reserve == 0.0
    finally:
        h.pipeline.stop()
    # an operator-configured promotion path keeps the reserve
    h2 = build_handlers(cache, batching=True,
                        batch_config=BatchConfig(critical_reserve=0.1),
                        classify_config=ClassifyConfig(
                            critical_users=("deploy-bot*",)))
    try:
        assert h2.pipeline.config.critical_reserve == 0.1
    finally:
        h2.pipeline.stop()


# ---------------------------------------------------------------------------
# per-class SLO windows + the cached burn accessor
# (observability/analytics.py)


def test_slo_per_class_windows_and_gauges():
    from kyverno_tpu.observability.analytics import SloTracker
    from kyverno_tpu.observability.metrics import MetricsRegistry

    clock = {"t": 1000.0}
    reg = MetricsRegistry()
    slo = SloTracker(metrics=reg, clock=lambda: clock["t"])
    slo.config.admission_p99_target_ms = 50.0
    slo.config.admission_error_budget = 0.01
    for _ in range(10):
        slo.record_admission(0.005, cls="critical")
    for _ in range(10):
        slo.record_admission(0.5, cls="bulk")
    state = slo.state()
    w = state["admission"]["windows"]["5m"]
    assert w["requests"] == 20 and w["slow"] == 10
    assert w["by_class"]["critical"]["slow"] == 0
    assert w["by_class"]["bulk"]["slow"] == 10
    assert w["by_class"]["bulk"]["burn_rate"] > 1.0
    slo.update_gauges()
    assert reg.slo_admission_burn.value({"window": "5m",
                                         "class": "bulk"}) > 1.0
    assert reg.slo_admission_burn.value({"window": "5m",
                                         "class": "critical"}) == 0.0


def test_admission_burn_fast_cached():
    from kyverno_tpu.observability.analytics import SloTracker

    clock = {"t": 1000.0}
    slo = SloTracker(clock=lambda: clock["t"])
    slo.config.admission_p99_target_ms = 50.0
    slo.config.admission_error_budget = 0.01
    for _ in range(10):
        slo.record_admission(0.005)
    for _ in range(10):
        slo.record_admission(0.5)
    burn = slo.admission_burn_fast()
    assert burn == pytest.approx((10 / 20) / 0.01)
    # cached: new samples inside max_age do not change the reading...
    for _ in range(100):
        slo.record_admission(0.5)
    assert slo.admission_burn_fast() == burn
    # ...until the cache ages out
    clock["t"] += 1.0
    assert slo.admission_burn_fast() > burn


# ---------------------------------------------------------------------------
# per-class metric families are exposed


def test_class_metric_families_in_exposition():
    from kyverno_tpu.observability.metrics import MetricsRegistry

    reg = MetricsRegistry()
    reg.serving_class_queue_depth.set(3, {"class": "bulk"})
    reg.serving_class_requests.inc({"class": "critical",
                                    "outcome": "batched"})
    reg.serving_hedge.inc({"winner": "scalar"})
    reg.serving_shed_total.inc({"outcome": "rejected", "class": "bulk",
                                "reason": "burn"})
    text = reg.exposition()
    assert 'kyverno_serving_class_queue_depth{class="bulk"} 3' in text
    assert 'kyverno_serving_class_requests_total{class="critical"' in text
    assert 'kyverno_serving_hedge_total{winner="scalar"} 1' in text
    assert 'reason="burn"' in text


def test_pipeline_publishes_class_metrics_and_state():
    from kyverno_tpu.observability.metrics import MetricsRegistry

    reg = MetricsRegistry()
    p = AdmissionPipeline(
        lambda payloads: [("ok", x) for x in payloads if x is not None],
        config=_sched_cfg(max_batch_size=4), metrics=reg)
    try:
        p.submit("c1", cls=CRIT)
        p.submit("d1", cls=DFLT)
    finally:
        p.stop()
    assert reg.serving_class_requests.value(
        {"class": "critical", "outcome": "batched"}) == 1
    state = p.state()
    assert state["stats"]["by_class"]["critical"]["evaluated"] == 1
    assert "class_weights" in state["config"]
    assert "queue_depth_by_class" in state
