"""Multi-device sharded scan on the 8-way virtual CPU mesh."""

import numpy as np

import jax

from kyverno_tpu.parallel import ShardedScanner, make_mesh
from kyverno_tpu.policies import load_pss_policies
from kyverno_tpu.policy.autogen import expand_policy
from kyverno_tpu.tpu.engine import TpuEngine
from kyverno_tpu.tpu.flatten import EncodeConfig


def pods(n):
    out = []
    for i in range(n):
        priv = [None, True, False][i % 3]
        sc = {"securityContext": {"privileged": priv}} if priv is not None else {}
        out.append({
            "apiVersion": "v1", "kind": "Pod",
            "metadata": {"name": f"p{i}", "namespace": "default"},
            "spec": {"containers": [{"name": "c", "image": "nginx", **sc}]},
        })
    return out


def test_sharded_scan_matches_single_device():
    assert len(jax.devices()) == 8  # conftest forces the virtual mesh
    policies = [expand_policy(p) for p in load_pss_policies(subset="disallow")]
    resources = pods(33)  # deliberately not divisible by 8
    scanner = ShardedScanner(policies, mesh=make_mesh())
    verdicts, counts = scanner.scan_device(resources)
    # single-device reference through the TpuEngine path
    eng = TpuEngine(policies)
    expected = eng.scan(resources)
    table = np.stack([expected.verdicts[i] for i, e in enumerate(eng.cps.rules)
                      if e.device_row is not None])
    assert verdicts.shape == table.shape
    assert (verdicts == table).all()
    # counts include padding lanes as NOT_MATCHED; real cells agree
    for r in range(verdicts.shape[0]):
        for c in range(6):
            real = int((verdicts[r] == c).sum())
            pad = scanner.pad(33) - 33
            exp = real + (pad if c == 3 else 0)
            assert counts[r, c] == exp


def test_sharded_scan_resolves_host_verdicts():
    policies = [expand_policy(p) for p in load_pss_policies(subset="disallow-privileged")]
    # a resource exceeding the row cap forces per-resource host fallback
    big = {
        "apiVersion": "v1", "kind": "Pod",
        "metadata": {"name": "big", "namespace": "default"},
        "spec": {"containers": [
            {"name": f"c{i}", "image": "nginx",
             "securityContext": {"privileged": i == 0}} for i in range(80)
        ]},
    }
    scanner = ShardedScanner(policies, mesh=make_mesh(),
                             encode_cfg=EncodeConfig(max_rows=64))
    result = scanner.scan(pods(4) + [big])
    assert (result.verdicts != 5).all()  # HOST never escapes scan()
    assert len(result.rules) == len(scanner.cps.rules)  # host rules included
    row = [i for i, (p, r) in enumerate(result.rules) if r == "privileged-containers"][0]
    assert result.verdicts[row, 4] == 2  # big pod fails via scalar completion


def test_scan_stream_tiled_matches_scan():
    """Tiled streaming scan (bench config #2's e2e path) must agree with
    the one-shot scan and the scalar-complete TpuEngine result."""
    policies = [expand_policy(p) for p in load_pss_policies(subset="disallow")]
    scanner = ShardedScanner(policies)
    resources = pods(41)
    result, stats = scanner.scan_stream(resources, tile=16)
    assert stats["tiles"] == 3 and result.verdicts.shape[1] == 41
    whole = TpuEngine.from_compiled(scanner.cps).scan(resources)
    np.testing.assert_array_equal(result.verdicts, whole.verdicts)
    assert result.rules == whole.rules
