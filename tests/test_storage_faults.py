"""Storage fault matrix + degraded-storage ladder (ISSUE 19).

Every durability surface routes through the ``resilience/storage``
shim, so one fault grammar (``storage.write:enospc:match=reports``)
can make any surface's disk fail with a REAL ``OSError`` — the same
except-clause a genuinely full, erroring, or read-only disk takes.
The contract under test, per surface:

- no exception escapes to the caller (verdict paths stay correct);
- the surface degrades: ``kyverno_storage_degraded{surface}`` flips
  to 1, errors count by kind, the op-log narrates the transition;
- the surface's memory mode is bit-identical (reports fold digest ==
  an undegraded twin; columnar reads off anonymous arenas == a fresh
  encode);
- disarm + a due re-probe heals: gauge back to 0, heal counted, and
  durability is re-established (reports compact the in-memory state
  to a snapshot a cold reopen recovers completely).

The slow legs drive a REAL serve subprocess: one with the fault armed
ambient through a churn scan (the ISSUE 19 acceptance), one with a
genuine OS failure manufactured via RLIMIT_FSIZE — proving injected
and real disk errors travel the same ladder.
"""

import errno
import http.client
import json
import os
import socket
import subprocess
import sys
import time

import numpy as np
import pytest

from kyverno_tpu.observability.metrics import global_registry as reg
from kyverno_tpu.resilience import storage as st
from kyverno_tpu.resilience.faults import FaultConfigError, global_faults


@pytest.fixture(autouse=True)
def _disarm_faults():
    global_faults.disarm()
    yield
    global_faults.disarm()


def _gauge(surface):
    return reg.storage_degraded.value({"surface": surface})


# ---------------------------------------------------------------------------
# the ladder itself


def test_classify_os_error_covers_the_matrix():
    assert st.classify_os_error(OSError(errno.ENOSPC, "x")) == "enospc"
    # EFBIG is how RLIMIT_FSIZE (the real-ENOSPC CI leg) surfaces
    assert st.classify_os_error(OSError(errno.EFBIG, "x")) == "enospc"
    assert st.classify_os_error(OSError(errno.EIO, "x")) == "eio"
    assert st.classify_os_error(OSError(errno.EROFS, "x")) == "erofs"
    assert st.classify_os_error(OSError(errno.EACCES, "x")) == "erofs"
    assert st.classify_os_error(OSError(errno.EPIPE, "x")) == "other"


def test_ladder_degrades_gates_probes_and_heals():
    clock = [0.0]
    h = st.StorageHealth("reports", clock=lambda: clock[0])
    assert h.allow()  # healthy: always
    assert h.record_error(OSError(errno.ENOSPC, "full"), op="write") \
        == "enospc"
    assert h.degraded
    assert _gauge("reports") == 1.0
    # no probe due yet: writes are counted drops
    assert not h.allow()
    assert h.state()["drops"] == 1
    clock[0] += 100.0
    assert h.allow()       # the due probe consumes the slot...
    assert not h.allow()   # ...so a concurrent writer is still dropped
    assert h.record_success() is True   # heal transition, exactly once
    assert h.record_success() is False
    assert not h.degraded
    assert _gauge("reports") == 0.0
    s = h.state()
    assert s["errors"] == 1 and s["heals"] == 1 and s["probes"] == 1
    assert s["last_kind"] == "enospc" and s["last_errno"] == errno.ENOSPC
    assert reg.storage_heals.value({"surface": "reports"}) >= 1


def test_os_error_modes_only_arm_at_storage_sites():
    with pytest.raises(FaultConfigError):
        global_faults.arm("tpu.dispatch", mode="enospc")
    with pytest.raises(FaultConfigError):
        global_faults.arm("reports.journal", mode="eio")
    with pytest.raises(FaultConfigError):
        global_faults.arm("storage.open", mode="short")  # write-only mode
    global_faults.arm("storage.write", mode="short")  # fine


def test_injected_enospc_is_a_real_oserror_scoped_by_surface(tmp_path):
    errors0 = reg.storage_errors.value({"surface": "reports",
                                        "kind": "enospc"})
    global_faults.arm("storage.write", mode="enospc", match="reports")
    fh = st.open_append(str(tmp_path / "j.wal"), st.SURFACE_REPORTS,
                        binary=True)
    with pytest.raises(OSError) as ei:
        st.write_frame(fh, b"x" * 16, st.SURFACE_REPORTS,
                       path=str(tmp_path / "j.wal"))
    fh.close()
    assert ei.value.errno == errno.ENOSPC
    assert st.storage_health(st.SURFACE_REPORTS).degraded
    assert reg.storage_errors.value(
        {"surface": "reports", "kind": "enospc"}) == errors0 + 1
    # match=reports scopes the fault: the oplog surface writes fine
    fh2 = st.open_append(str(tmp_path / "op.jsonl"), st.SURFACE_OPLOG)
    st.write_frame(fh2, "ok\n", st.SURFACE_OPLOG)
    fh2.close()
    assert not st.storage_health(st.SURFACE_OPLOG).degraded


def test_short_write_tears_a_real_prefix_then_raises_eio(tmp_path):
    global_faults.arm("storage.write", mode="short", count=1)
    path = tmp_path / "seg.ndjson"
    fh = st.open_truncate(str(path), st.SURFACE_FLIGHT)
    with pytest.raises(OSError) as ei:
        st.write_frame(fh, "0123456789", st.SURFACE_FLIGHT, path=str(path))
    fh.close()
    assert ei.value.errno == errno.EIO
    assert path.read_text() == "01234"  # the torn half really landed


# ---------------------------------------------------------------------------
# surface: reports — memory-only folding, bit-identical, compact-on-heal


def _put(store, i, mark="a"):
    store.apply(f"u{i}", f"sha-{mark}-{i}", "ps", f"ns{i % 2}", "Pod",
                f"p{i}", [("pol", "r", "fail" if i % 3 == 0 else "pass")])


@pytest.mark.parametrize("kind", ["enospc", "eio", "erofs", "short"])
def test_reports_fold_memory_only_then_heal_recovers_all(tmp_path, kind):
    from kyverno_tpu.reports.store import ReportStore

    d = str(tmp_path / "rep")
    store = ReportStore(directory=d)
    twin = ReportStore(directory=None)  # the undegraded oracle
    _put(store, 0)
    _put(twin, 0)
    global_faults.arm("storage.write", mode=kind, match="reports")
    for i in range(1, 8):  # must not raise: memory-only folding
        _put(store, i)
        _put(twin, i)
    h = st.storage_health(st.SURFACE_REPORTS)
    assert h.degraded
    assert _gauge("reports") == 1.0
    assert reg.storage_errors.value({"surface": "reports", "kind":
                                     "eio" if kind == "short" else kind}) > 0
    # the degraded fold is bit-identical to the never-degraded twin
    assert store.digest() == twin.digest()
    assert store.verify_rebuild()
    # disarm -> due probe -> the next fold lands AND compaction
    # re-establishes durability for every row folded in memory
    global_faults.disarm()
    h.force_probe()
    _put(store, 99)
    _put(twin, 99)
    assert not h.degraded
    assert _gauge("reports") == 0.0
    assert reg.storage_heals.value({"surface": "reports"}) >= 1
    assert store.stats["compactions"] >= 1
    store.close(compact=False)  # dirty close: disk must already be whole
    recovered = ReportStore(directory=d)
    assert recovered.digest() == twin.digest()
    assert recovered.verify_rebuild()
    recovered.close()
    twin.close()


def test_reports_unwritable_dir_at_boot_folds_in_memory(tmp_path):
    from kyverno_tpu.reports.store import ReportStore

    global_faults.arm("storage.open", mode="erofs", match="reports")
    store = ReportStore(directory=str(tmp_path / "ro"))  # must not raise
    _put(store, 1)
    assert st.storage_health(st.SURFACE_REPORTS).degraded
    assert store.state()["resources"] == 1
    assert store.verify_rebuild()
    store.close(compact=False)


# ---------------------------------------------------------------------------
# surface: columnar — anonymous arenas, bit-identical reads, remount


def _pod(i, app="a"):
    return {
        "apiVersion": "v1", "kind": "Pod",
        "metadata": {"name": f"p{i}", "namespace": "default",
                     "uid": f"uid-{i}", "labels": {"app": f"{app}{i % 3}"}},
        "spec": {"containers": [
            {"name": "c", "image": "nginx:1.25",
             "securityContext": {"privileged": i % 2 == 0}}]},
    }


def test_columnar_drops_to_anonymous_arenas_and_remounts(tmp_path):
    from kyverno_tpu.cluster.columnar import ColumnarStore
    from kyverno_tpu.tpu.cache import extract_rows, resource_content_hash
    from kyverno_tpu.tpu.flatten import EncodeConfig, encode_resources

    cfg = EncodeConfig()
    store = ColumnarStore(directory=str(tmp_path / "col"))
    pods = [_pod(i) for i in range(6)]
    for r in pods:
        store.warm(cfg, (), (), r, resource_content_hash(r))
    store.sync()  # healthy: arenas + manifests on disk
    assert not st.storage_health(st.SURFACE_COLUMNAR).degraded

    global_faults.arm("storage.write", mode="eio", match="columnar")
    pods = [_pod(i, app="b") for i in range(6)]  # churn: new rows
    for r in pods:
        store.warm(cfg, (), (), r, resource_content_hash(r))
    store.sync()  # must not raise: tables drop to anonymous arenas
    h = st.storage_health(st.SURFACE_COLUMNAR)
    assert h.degraded
    assert _gauge("columnar") == 1.0
    assert any(t["memory_only"] for t in store.state()["tables"])

    # reads off the anonymous arenas stay bit-identical
    ekey = store.encode_key(cfg, (), ())
    for r in pods:
        e = store.get_entry(ekey, resource_content_hash(r))
        assert e is not None
        ref = extract_rows(encode_resources([r], cfg, (), ()), 0)
        assert e.n_rows == ref.n_rows
        for name in ref.lanes:
            assert np.array_equal(e.lanes[name], ref.lanes[name]), name

    global_faults.disarm()
    h.force_probe()
    store.sync()  # due probe: remount the mmap backing + flush
    assert not h.degraded
    assert _gauge("columnar") == 0.0
    assert all(not t["memory_only"] for t in store.state()["tables"])
    assert all(t["mmap"] for t in store.state()["tables"])
    # the remounted backing survives a cold restart with the rows intact
    reopened = ColumnarStore(directory=str(tmp_path / "col"))
    for r in pods:
        e = reopened.get_entry(ekey, resource_content_hash(r))
        assert e is not None
        ref = extract_rows(encode_resources([r], cfg, (), ()), 0)
        for name in ref.lanes:
            assert np.array_equal(e.lanes[name], ref.lanes[name]), name


# ---------------------------------------------------------------------------
# surfaces: flight spool + divergences — drop-and-count, independent


def test_spool_vs_divergence_surfaces_independent(tmp_path):
    # NB: the test name must not contain a surface name — tmp_path
    # embeds it, and match=<surface> greps the full "<surface>:<path>"
    # payload (that substring semantic is exactly what scopes a chaos
    # run to one surface in production paths)
    from kyverno_tpu.observability.flightrecorder import (global_flight,
                                                          load_capture)

    global_flight.configure(capacity=16, sample_rate=1.0,
                            spool_dir=str(tmp_path / "spool"))
    for i in range(4):
        global_flight.record_admission(
            {"kind": "Pod", "metadata": {"name": f"p{i}"}},
            [(("pol", "r"), 0)], "batched")

    global_faults.arm("storage.write", mode="enospc", match="flight_spool")
    assert global_flight.spool(force=True) is None  # counted drop
    assert st.storage_health(st.SURFACE_FLIGHT).degraded
    assert len(global_flight) == 4  # the in-memory ring keeps recording

    # the divergence surface is its OWN ladder: evidence still lands
    path = global_flight.spool_divergence(
        {"seq": 1, "resource": {"kind": "Pod"}},
        [(("pol", "r"), 0)], [(("pol", "r"), 2)])
    assert path is not None
    assert not st.storage_health(st.SURFACE_DIVERGENCES).degraded
    assert load_capture(path)

    global_faults.disarm()
    st.storage_health(st.SURFACE_FLIGHT).force_probe()
    out = global_flight.spool(force=True)  # the probe spool heals
    assert out is not None
    assert not st.storage_health(st.SURFACE_FLIGHT).degraded
    assert reg.storage_heals.value({"surface": "flight_spool"}) >= 1
    assert len(load_capture(out)) == 4


# ---------------------------------------------------------------------------
# surface: oplog — file sink drop-and-count, stderr untouched, no deadlock


def test_oplog_file_sink_drops_counts_and_heals(tmp_path):
    from kyverno_tpu.observability.log import global_oplog

    path = tmp_path / "op.jsonl"
    global_oplog.configure(path=str(path), stderr=False)
    global_oplog.emit("healthy")
    global_faults.arm("storage.write", mode="eio", match="oplog")
    for _ in range(5):
        global_oplog.emit("sick")  # must not raise, must not deadlock
    h = st.storage_health(st.SURFACE_OPLOG)
    assert h.degraded
    assert _gauge("oplog") == 1.0
    assert h.state()["drops"] > 0

    global_faults.disarm()
    h.force_probe()
    global_oplog.emit("healed")
    assert not h.degraded
    events = [json.loads(ln)["event"]
              for ln in path.read_text().splitlines() if ln.strip()]
    assert "healthy" in events and "healed" in events
    assert "sick" not in events          # dropped, not torn
    assert "storage_healed" in events    # the ladder narrates itself
    global_oplog.reset()


def test_oplog_unopenable_sink_degrades_instead_of_raising(tmp_path):
    from kyverno_tpu.observability.log import global_oplog

    global_faults.arm("storage.open", mode="erofs", match="oplog")
    global_oplog.configure(path=str(tmp_path / "op.jsonl"), stderr=False)
    assert st.storage_health(st.SURFACE_OPLOG).degraded
    global_oplog.emit("while-down")  # no raise
    global_faults.disarm()
    st.storage_health(st.SURFACE_OPLOG).force_probe()
    global_oplog.emit("back")  # the probe retries the open itself
    assert not st.storage_health(st.SURFACE_OPLOG).degraded
    assert os.path.exists(tmp_path / "op.jsonl")
    global_oplog.reset()


# ---------------------------------------------------------------------------
# surface: trace_export — exporter born degraded reopens on probe


def test_trace_exporter_degrades_at_birth_and_reopens(tmp_path):
    from kyverno_tpu.observability.tracing import (OTLPJsonFileExporter,
                                                   Tracer)

    path = str(tmp_path / "trace.otlp.jsonl")
    global_faults.arm("storage.open", mode="erofs", match="trace_export")
    tr = Tracer(exporter=OTLPJsonFileExporter(path))  # must not raise
    with tr.span("while-down"):
        pass
    h = st.storage_health(st.SURFACE_TRACE)
    assert h.degraded
    assert _gauge("trace_export") == 1.0

    global_faults.disarm()
    h.force_probe()
    with tr.span("after-heal"):
        pass
    assert not h.degraded
    lines = [json.loads(x) for x in open(path).read().splitlines()]
    names = [ln["resourceSpans"][0]["scopeSpans"][0]["spans"][0]["name"]
             for ln in lines]
    assert names == ["after-heal"]  # dropped span dropped, healed span real


# ---------------------------------------------------------------------------
# surface: xla_cache — unwritable dir disables the cache, never a compile


def test_xla_cache_unwritable_dir_disables_persistent_cache(tmp_path,
                                                            monkeypatch):
    import kyverno_tpu.tpu.cache as cache_mod
    from kyverno_tpu.observability.log import global_oplog

    monkeypatch.setattr(cache_mod, "_xla_cache_dir", None)
    seen = []
    monkeypatch.setattr(global_oplog, "emit",
                        lambda event, **kw: seen.append(event))
    # makedirs(exist_ok=True) succeeds on an existing dir even on a
    # read-only mount — only the probe-file write catches this
    global_faults.arm("storage.write", mode="erofs", match="xla_cache")
    assert cache_mod.enable_xla_compile_cache(str(tmp_path / "xla")) is None
    assert cache_mod.xla_cache_dir() is None
    h = st.storage_health(st.SURFACE_XLA_CACHE)
    assert h.degraded
    assert "xla_cache_disabled" in seen
    global_faults.disarm()
    h.force_probe()
    st.probe_writable(str(tmp_path), st.SURFACE_XLA_CACHE)
    assert not h.degraded
    assert reg.storage_heals.value({"surface": "xla_cache"}) >= 1


# ---------------------------------------------------------------------------
# the /debug/state + /readyz surfaces


def test_debug_state_and_readyz_carry_storage_advisory():
    from kyverno_tpu.api.policy import ClusterPolicy
    from kyverno_tpu.cluster.policycache import PolicyCache
    from kyverno_tpu.cluster.snapshot import ClusterSnapshot
    from kyverno_tpu.webhooks.server import Handlers, handle_debug_path

    cache = PolicyCache()
    cache.set(ClusterPolicy.from_dict({
        "apiVersion": "kyverno.io/v1", "kind": "ClusterPolicy",
        "metadata": {"name": "storage-dbg"},
        "spec": {"validationFailureAction": "Enforce", "rules": [{
            "name": "named",
            "match": {"any": [{"resources": {"kinds": ["Pod"]}}]},
            "validate": {"message": "m",
                         "pattern": {"metadata": {"name": "?*"}}},
        }]}}))
    h = Handlers(cache, ClusterSnapshot(), batching=True)
    try:
        st.storage_health(st.SURFACE_REPORTS).record_error(
            OSError(errno.ENOSPC, "full"), op="write")
        status, body, _ = handle_debug_path("/debug/state", h)
        assert status == 200
        doc = json.loads(body)
        assert doc["storage"]["reports"]["state"] == "degraded"
        assert doc["storage"]["reports"]["last_kind"] == "enospc"
        assert st.global_storage.degraded_surfaces() == ["reports"]
        ok, detail = h.ready()
        assert ok  # degraded storage NEVER flips readiness
        assert detail["storage_degraded"] == ["reports"]
        st.storage_health(st.SURFACE_REPORTS).record_success()
        _, detail = h.ready()
        assert "storage_degraded" not in detail
    finally:
        h.pipeline.stop()
        h.batcher.stop()


# ---------------------------------------------------------------------------
# slow legs: a REAL serve process under ambient + genuine disk failure


N_PODS = 60


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _get(port, path, timeout=30):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=timeout)
    try:
        conn.request("GET", path)
        resp = conn.getresponse()
        return resp.status, resp.read()
    finally:
        conn.close()


def _post(port, path, doc, timeout=300):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=timeout)
    try:
        conn.request("POST", path, json.dumps(doc),
                     {"Content-Type": "application/json"})
        resp = conn.getresponse()
        return resp.status, resp.read()
    finally:
        conn.close()


def _serve_pods(n, mark="a"):
    return [{
        "apiVersion": "v1", "kind": "Pod",
        "metadata": {"name": f"pod-{i}", "namespace": f"ns{i % 4}",
                     "uid": f"u-{i}", "labels": {"rev": mark}},
        "spec": {"containers": [{
            "name": "c", "image": "nginx",
            **({"securityContext": {"privileged": True}}
               if i % 3 == 0 else {})}]},
    } for i in range(n)]


def _metric(text, name, **labels):
    total = 0.0
    for line in text.splitlines():
        if not line.startswith(name):
            continue
        rest = line[len(name):]
        if rest and rest[0] not in ("{", " "):
            continue
        if all(f'{k}="{v}"' in rest for k, v in labels.items()):
            try:
                total += float(line.split(" # ")[0].rsplit(" ", 1)[-1])
            except ValueError:
                pass
    return total


def _policy_yaml(tmp_path):
    import yaml

    policy_file = tmp_path / "policy.yaml"
    policy_file.write_text(yaml.safe_dump({
        "apiVersion": "kyverno.io/v1", "kind": "ClusterPolicy",
        "metadata": {"name": "storage-chaos"},
        "spec": {"validationFailureAction": "Enforce", "rules": [{
            "name": "no-privileged",
            "match": {"any": [{"resources": {"kinds": ["Pod"]}}]},
            "validate": {"message": "no privileged",
                         "pattern": {"spec": {"containers": [
                             {"=(securityContext)":
                              {"=(privileged)": "false"}}]}}},
        }]}}))
    return policy_file


@pytest.fixture
def serve_procs():
    procs = []
    yield procs
    for p in procs:
        if p.poll() is None:
            p.terminate()
    for p in procs:
        try:
            p.wait(timeout=15)
        except subprocess.TimeoutExpired:
            p.kill()
            p.wait(timeout=5)


def _wait_ready(p, metrics_port, timeout=300):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if p.poll() is not None:
            raise AssertionError(
                "serve died at boot:\n" + (p.stderr.read() or "")[-3000:])
        try:
            status, _ = _get(metrics_port, "/healthz", timeout=2)
            if status == 200:
                return
        except OSError:
            pass
        time.sleep(0.3)
    raise AssertionError("serve never became healthy")


@pytest.mark.slow
def test_ambient_enospc_churn_scan_degrades_heals_bit_identical(
        tmp_path, serve_procs):
    """ISSUE 19 acceptance: storage.write:enospc armed ambient on the
    reports surface through a churn scan — zero escaped exceptions,
    zero verdict divergence at shadow-verify 1.0, the degraded gauge
    raised while sick, then (the injected fault exhausts its count=5
    budget against the capped re-probes) the store heals, compacts,
    and the offline --rebuild-check recovers bit-identically."""
    policy_file = _policy_yaml(tmp_path)
    reports_dir = tmp_path / "reports"
    env = dict(os.environ)
    env.update({"JAX_PLATFORMS": "cpu",
                "KYVERNO_TPU_XLA_CACHE_DIR": str(tmp_path / "xla"),
                # fires on the first 5 matched storage writes (the
                # first journal append + the next 4 re-probes), then
                # the disk "recovers" — the heal path needs no disarm
                # endpoint, exactly like space being freed
                "KYVERNO_TPU_FAULTS":
                    "storage.write:enospc:match=reports,count=5"})
    metrics_port = _free_port()
    p = subprocess.Popen(
        [sys.executable, "-m", "kyverno_tpu", "serve", str(policy_file),
         "--port", "0", "--metrics-port", str(metrics_port),
         "--scan-interval", "9999", "--batching",
         "--reports-dir", str(reports_dir),
         "--shadow-verify-rate", "1.0",
         "--flight-sample-rate", "1.0"],
        env=env, stdout=subprocess.DEVNULL, stderr=subprocess.PIPE,
        text=True)
    serve_procs.append(p)
    _wait_ready(p, metrics_port)

    for pod in _serve_pods(N_PODS):
        status, _ = _post(metrics_port, "/snapshot/upsert", pod)
        assert status == 200
    status, body = _post(metrics_port, "/scan", {"full": True})
    assert status == 200
    assert json.loads(body)["scanned"] == N_PODS

    # the first journal append degraded the surface; readiness is
    # NEVER flipped by sick storage (it is an advisory)
    status, body = _get(metrics_port, "/metrics")
    text = body.decode()
    assert _metric(text, "kyverno_storage_degraded", surface="reports") == 1
    assert _metric(text, "kyverno_storage_errors_total",
                   surface="reports", kind="enospc") >= 1
    status, body = _get(metrics_port, "/readyz")
    assert status == 200
    detail = json.loads(body)
    assert detail.get("storage_degraded") == ["reports"]
    status, body = _get(metrics_port, "/debug/state")
    assert json.loads(body)["storage"]["reports"]["state"] == "degraded"

    # churn: mutate every pod + rescan to keep folds (and re-probes)
    # flowing until the fault budget exhausts and a probe append heals
    healed = False
    deadline = time.monotonic() + 120
    rev = 0
    while time.monotonic() < deadline:
        rev += 1
        for pod in _serve_pods(N_PODS, mark=f"r{rev}"):
            _post(metrics_port, "/snapshot/upsert", pod)
        status, _ = _post(metrics_port, "/scan", {"full": True})
        assert status == 200  # zero exceptions escape throughout
        _, body = _get(metrics_port, "/metrics")
        text = body.decode()
        if _metric(text, "kyverno_storage_degraded", surface="reports") == 0 \
                and _metric(text, "kyverno_storage_heals_total",
                            surface="reports") >= 1:
            healed = True
            break
        time.sleep(2.0)
    assert healed, "reports surface never healed after the fault budget"

    # shadow verification at rate 1.0 saw zero divergence end to end
    def matches():
        _, b = _get(metrics_port, "/metrics")
        return _metric(b.decode(), "kyverno_verification_checks_total",
                       result="match")

    deadline = time.monotonic() + 60
    while time.monotonic() < deadline and matches() == 0:
        time.sleep(0.5)
    _, body = _get(metrics_port, "/metrics")
    text = body.decode()
    assert _metric(text, "kyverno_verification_divergence_total") == 0
    assert _metric(text, "kyverno_verification_checks_total",
                   result="match") > 0
    status, body = _get(metrics_port, "/readyz")
    assert status == 200
    assert "storage_degraded" not in json.loads(body)

    p.terminate()
    p.wait(timeout=15)

    # heal-time compaction made the in-memory folds durable: the
    # offline oracle recovers every row bit-identically
    cli_env = dict(env)
    cli_env.pop("KYVERNO_TPU_FAULTS")
    cli = subprocess.run(
        [sys.executable, "-m", "kyverno_tpu", "report", str(reports_dir),
         "--rebuild-check", "--json"],
        env=cli_env, capture_output=True, text=True, timeout=120)
    assert cli.returncode == 0, cli.stderr[-2000:]
    doc = json.loads(cli.stdout)
    assert doc["rebuild_identical"] is True
    assert doc["state"]["resources"] == N_PODS


@pytest.mark.slow
def test_real_enospc_via_rlimit_fsize_shares_the_injected_path(
        tmp_path, serve_procs):
    """No fault armed at all: the child's RLIMIT_FSIZE makes the
    journal writes genuinely fail (EFBIG, SIGXFSZ ignored) once the
    WAL crosses the limit — and the SAME ladder the injected tests
    exercised absorbs it: degraded+counted (kind=enospc), serving and
    readiness stay green, zero divergence."""
    policy_file = _policy_yaml(tmp_path)
    reports_dir = tmp_path / "reports"
    env = dict(os.environ)
    env.update({"JAX_PLATFORMS": "cpu",
                # persistent XLA cache writes would trip the rlimit too
                "KYVERNO_TPU_XLA_CACHE_DIR": "none"})
    env.pop("KYVERNO_TPU_FAULTS", None)
    metrics_port = _free_port()
    bootstrap = (
        "import resource, signal, sys, runpy;"
        "signal.signal(signal.SIGXFSZ, signal.SIG_IGN);"
        "resource.setrlimit(resource.RLIMIT_FSIZE, (8192, 8192));"
        "sys.argv = ['kyverno_tpu'] + sys.argv[1:];"
        "runpy.run_module('kyverno_tpu', run_name='__main__')")
    p = subprocess.Popen(
        [sys.executable, "-c", bootstrap, "serve", str(policy_file),
         "--port", "0", "--metrics-port", str(metrics_port),
         "--scan-interval", "9999", "--batching",
         "--reports-dir", str(reports_dir),
         "--shadow-verify-rate", "1.0",
         "--flight-sample-rate", "1.0"],
        env=env, stdout=subprocess.DEVNULL, stderr=subprocess.PIPE,
        text=True)
    serve_procs.append(p)
    _wait_ready(p, metrics_port)

    for pod in _serve_pods(N_PODS):
        status, _ = _post(metrics_port, "/snapshot/upsert", pod)
        assert status == 200
    # ~60 journaled folds blow through the 8 KiB cap mid-scan
    status, body = _post(metrics_port, "/scan", {"full": True})
    assert status == 200
    assert json.loads(body)["scanned"] == N_PODS

    _, body = _get(metrics_port, "/metrics")
    text = body.decode()
    assert _metric(text, "kyverno_storage_degraded", surface="reports") == 1
    # EFBIG classifies as the space-exhaustion kind: one code path
    assert _metric(text, "kyverno_storage_errors_total",
                   surface="reports", kind="enospc") >= 1
    status, body = _get(metrics_port, "/readyz")
    assert status == 200  # advisory only, never flips readiness
    assert json.loads(body).get("storage_degraded") == ["reports"]

    # the engine keeps serving scans correctly on the sick disk
    status, body = _post(metrics_port, "/scan", {"full": True})
    assert status == 200
    assert json.loads(body)["scanned"] == N_PODS

    def matches():
        _, b = _get(metrics_port, "/metrics")
        return _metric(b.decode(), "kyverno_verification_checks_total",
                       result="match")

    deadline = time.monotonic() + 60
    while time.monotonic() < deadline and matches() == 0:
        time.sleep(0.5)
    _, body = _get(metrics_port, "/metrics")
    text = body.decode()
    assert _metric(text, "kyverno_verification_divergence_total") == 0
    assert _metric(text, "kyverno_verification_checks_total",
                   result="match") > 0
    assert p.poll() is None, "serve must survive a genuinely full disk"
