"""Flattener / metadata encoder unit tests."""

import numpy as np

from kyverno_tpu.tpu import flatten
from kyverno_tpu.tpu.flatten import EncodeConfig, T_ARR, T_BOOL, T_MAP, T_NUM, T_STR, encode_resources
from kyverno_tpu.tpu.hashing import hash_path, hash_str, split32
from kyverno_tpu.tpu.metadata import encode_metadata

POD = {
    "apiVersion": "v1",
    "kind": "Pod",
    "metadata": {"name": "web", "namespace": "prod", "labels": {"app": "web"}},
    "spec": {
        "hostNetwork": False,
        "containers": [
            {"name": "a", "image": "nginx", "securityContext": {"privileged": True}},
            {"name": "b", "image": "redis:7", "resources": {"limits": {"memory": "100Mi"}}},
        ],
    },
}


def _row(batch, i, segs):
    h, l = split32(hash_path(segs))
    mask = (batch.norm_hi[i] == h) & (batch.norm_lo[i] == l) & (batch.valid[i] == 1)
    idx = np.nonzero(mask)[0]
    return idx


def test_row_paths_and_types():
    b = encode_resources([POD])
    (r,) = _row(b, 0, ("spec", "hostNetwork"))
    assert b.type_tag[0, r] == T_BOOL and b.bool_val[0, r] == 0
    (r,) = _row(b, 0, ("spec", "containers"))
    assert b.type_tag[0, r] == T_ARR and b.arr_len[0, r] == 2
    rows = _row(b, 0, ("spec", "containers", "[]"))
    assert len(rows) == 2
    assert sorted(b.scope1[0, rows].tolist()) == [0, 1]
    rows = _row(b, 0, ("spec", "containers", "[]", "image"))
    assert len(rows) == 2
    assert all(b.type_tag[0, r] == T_STR for r in rows)


def test_scope_indices_follow_elements():
    b = encode_resources([POD])
    rows = _row(b, 0, ("spec", "containers", "[]", "securityContext", "privileged"))
    (r,) = rows
    assert b.scope1[0, r] == 0  # only container a has privileged


def test_numeric_lanes():
    b = encode_resources([{"a": 2, "b": "2", "c": "2.0", "d": 2.0, "e": "100Mi"}])
    (ra,) = _row(b, 0, ("a",))
    (rb,) = _row(b, 0, ("b",))
    (rc,) = _row(b, 0, ("c",))
    (rd,) = _row(b, 0, ("d",))
    (re_,) = _row(b, 0, ("e",))
    # canonical number hash: 2 == "2" == 2.0 collapse; "2.0" only via float grammar
    assert (b.num_hi[0, ra], b.num_lo[0, ra]) == (b.num_hi[0, rb], b.num_lo[0, rb])
    assert (b.num_hi[0, ra], b.num_lo[0, ra]) == (b.num_hi[0, rd], b.num_lo[0, rd])
    assert (b.num_hi[0, rc], b.num_lo[0, rc]) == (b.num_hi[0, ra], b.num_lo[0, ra])
    assert b.str_goint[0, rb] == 1 and b.str_goint[0, rc] == 0 and b.str_gofloat[0, rc] == 1
    # quantity lane: 100Mi parses
    assert b.has_qty[0, re_] == 1 and b.qty_val[0, re_] == np.float32(100 * 2**20)
    # "2" as quantity too
    assert b.has_qty[0, rb] == 1


def test_byte_pool_policy_aware():
    p = hash_path(("spec", "containers", "[]", "image"))
    b = encode_resources([POD], byte_paths={p})
    rows = _row(b, 0, ("spec", "containers", "[]", "image"))
    slots = b.byte_slot[0, rows]
    assert all(s >= 0 for s in slots)
    texts = set()
    for s in slots:
        n = b.pool_len[0, s]
        texts.add(bytes(b.pool[0, s, :n]).decode())
    assert texts == {"nginx", "redis:7"}
    # non-requested paths get no slot
    (r,) = _row(b, 0, ("metadata", "name"))
    assert b.byte_slot[0, r] == -1


def test_overflow_flags_fallback():
    big = {"items": [{"x": i} for i in range(40)]}
    b = encode_resources([big], EncodeConfig(max_rows=32))
    assert b.fallback[0] == 1
    b2 = encode_resources([POD])
    assert b2.fallback[0] == 0


def test_metadata_encoding():
    m = encode_metadata(
        [POD],
        namespace_labels={"prod": {"env": "prod"}},
        operations=["CREATE"],
    )
    assert tuple(m.kind_h[0]) == split32(hash_str("Pod", tag="K"))
    assert bytes(m.name_bytes[0, : m.name_len[0]]).decode() == "web"
    assert m.labels_n[0] == 1
    assert m.nsl_n[0] == 1
    assert m.op_code[0] == 1
    assert m.admission_empty[0] == 1
    ns = {"apiVersion": "v1", "kind": "Namespace", "metadata": {"name": "prod"}}
    m2 = encode_metadata([ns], namespace_labels={"prod": {"env": "prod"}})
    assert m2.is_namespace_kind[0] == 1
    assert m2.nsl_n[0] == 1  # Namespace resources join their own labels


def test_fast_encoder_matches_reference():
    """The memoized fast encoder must be lane-for-lane identical to the
    reference (slow) encoder over structurally diverse resources."""
    import numpy as np

    from kyverno_tpu.tpu.flatten import encode_resources_reference
    from kyverno_tpu.tpu.hashing import hash_path

    cases = [
        {}, {"a": None}, {"a": [1, 2.5, "3", True, None]},
        {"m": {"x*": "glob?", "q": "100Mi", "d": "1.5h", "n": "-42",
               "f": "1e3", "s": "word"}},
        {"deep": {"a": {"b": {"c": {"d": [[{"e": 1}]]}}}}},
        {"arr": [[{"k": i} for i in range(20)]]},   # depth-1 instance overflow
        {"big": [{"k": i} for i in range(20)]},     # depth-0 overflow -> fallback
        {"metadata": {"labels": {"app": "x", "tier*": "backend"}}},
        {"v": 2.0}, {"v": 0.001}, {"v": -0.0}, {"v": 0.0}, {"v": True},
        {"v": 10**25}, {"v": "0"}, {"v": ""},
        POD,
    ]
    bp = {hash_path(("spec", "containers", "[]", "image")),
          hash_path(("m", "q")), hash_path(("v",))}
    kbp = {hash_path(("metadata", "labels")), hash_path(("m",))}
    cfg = EncodeConfig()
    fast = encode_resources(cases, cfg, bp, kbp).arrays()
    ref = encode_resources_reference(cases, cfg, bp, kbp).arrays()
    for lane, got in fast.items():
        assert np.array_equal(got, ref[lane]), f"lane {lane} diverged"
