"""Device foreach_deny evaluation + membership glob fallback parity.

Covers the VERDICT round-1 regression (foreach rules compiled but not
evaluable) and the ADVICE has_glob bypass (resource values containing
*/? wildcard-match in membership operators on the scalar path; the
device must route those resources to host instead of silently passing).
"""

import numpy as np

from kyverno_tpu.policies import load_pss_policies
from kyverno_tpu.policy.autogen import expand_policy
from kyverno_tpu.tpu.compiler import compile_policy_set

from test_tpu_parity import check_parity, make_policy, pod


CAP_STRICT_FOREACH = {
    "name": "require-drop-all",
    "match": {"any": [{"resources": {"kinds": ["Pod"]}}]},
    "validate": {
        "message": "Containers must drop ALL capabilities.",
        "foreach": [
            {
                "list": "request.object.spec.[ephemeralContainers, initContainers, containers][]",
                "deny": {
                    "conditions": {
                        "all": [
                            {
                                "key": "ALL",
                                "operator": "AnyNotIn",
                                "value": "{{ element.securityContext.capabilities.drop[] || `[]` }}",
                            }
                        ]
                    }
                },
            }
        ],
    },
}

ADD_CAPS_FOREACH = {
    "name": "adding-capabilities-strict",
    "match": {"any": [{"resources": {"kinds": ["Pod"]}}]},
    "validate": {
        "message": "Only NET_BIND_SERVICE may be added.",
        "foreach": [
            {
                "list": "request.object.spec.[ephemeralContainers, initContainers, containers][]",
                "deny": {
                    "conditions": {
                        "all": [
                            {
                                "key": "{{ element.securityContext.capabilities.add[] || `[]` }}",
                                "operator": "AnyNotIn",
                                "value": ["NET_BIND_SERVICE"],
                            }
                        ]
                    }
                },
            }
        ],
    },
}


def ctr(name, drop=None, add=None, sc=False):
    c = {"name": name, "image": "nginx"}
    caps = {}
    if drop is not None:
        caps["drop"] = drop
    if add is not None:
        caps["add"] = add
    if caps or sc:
        c["securityContext"] = {"capabilities": caps} if caps else {}
    return c


def test_foreach_deny_compiles_to_device():
    policies = [make_policy("cap-strict", [CAP_STRICT_FOREACH, ADD_CAPS_FOREACH])]
    cps = compile_policy_set(policies)
    assert cps.coverage() == (2, 2), [e.fallback_reason for e in cps.rules]


def test_foreach_deny_parity():
    policies = [make_policy("cap-strict", [CAP_STRICT_FOREACH, ADD_CAPS_FOREACH])]
    resources = [
        # compliant: drops ALL, adds nothing
        pod("ok", spec={"containers": [ctr("a", drop=["ALL"])]}),
        # violates require-drop-all: drops only NET_RAW
        pod("bad-drop", spec={"containers": [ctr("a", drop=["NET_RAW"])]}),
        # violates: no securityContext at all (default [] => denied)
        pod("no-sc", spec={"containers": [ctr("a")]}),
        # empty capabilities map => drop missing => denied
        pod("empty-caps", spec={"containers": [ctr("a", sc=True)]}),
        # adds an extra capability => second rule fails
        pod("bad-add", spec={"containers": [ctr("a", drop=["ALL"], add=["SYS_ADMIN"])]}),
        # allowed add
        pod("ok-add", spec={"containers": [ctr("a", drop=["ALL"], add=["NET_BIND_SERVICE"])]}),
        # multiselect across init + main containers; one bad initContainer
        pod("init-bad", spec={
            "containers": [ctr("a", drop=["ALL"])],
            "initContainers": [ctr("i", drop=["CHOWN"])],
        }),
        # no containers at all: zero applied elements => skip
        pod("empty", spec={}),
        # non-Pod kind: not matched
        pod("svc", kind="Service", spec={}),
    ]
    check_parity(policies, resources)


def test_foreach_mixed_drop_lists_parity():
    policies = [make_policy("cap-strict", [CAP_STRICT_FOREACH])]
    resources = [
        # ALL present among others
        pod("multi", spec={"containers": [ctr("a", drop=["CHOWN", "ALL"])]}),
        # case-sensitive: "all" is not "ALL"
        pod("case", spec={"containers": [ctr("a", drop=["all"])]}),
        # two containers, second bad
        pod("two", spec={"containers": [ctr("a", drop=["ALL"]), ctr("b", drop=[])]}),
    ]
    check_parity(policies, resources)


def test_pss_bundle_foreach_rules_on_device():
    policies = [expand_policy(p) for p in load_pss_policies()]
    cps = compile_policy_set(policies)
    host = {e.policy_name for e in cps.rules if e.device_row is None}
    assert "disallow-capabilities-strict" not in host


GLOB_DENY_RULE = {
    "name": "deny-secret-volumes",
    "match": {"any": [{"resources": {"kinds": ["Pod"]}}]},
    "validate": {
        "message": "secret volumes are denied",
        "deny": {
            "conditions": {
                "any": [
                    {
                        "key": "{{ request.object.spec.volumes[].kind[] }}",
                        "operator": "AnyIn",
                        "value": ["secret"],
                    }
                ]
            }
        },
    },
}


def test_membership_glob_resource_value_falls_back_to_host():
    """ADVICE high: a resource value of '*' wildcard-matches any literal
    in scalar membership (conditions _wild_either); the device cannot
    reproduce that with hash equality and must yield the scalar verdict
    via host fallback instead of silently passing."""
    policies = [make_policy("glob-deny", [GLOB_DENY_RULE])]
    resources = [
        pod("wild", spec={"volumes": [{"kind": "*"}]}),      # scalar: denied
        pod("plain", spec={"volumes": [{"kind": "secret"}]}),  # denied
        pod("clean", spec={"volumes": [{"kind": "emptyDir"}]}),  # pass
        pod("question", spec={"volumes": [{"kind": "secre?"}]}),  # scalar: denied
    ]
    check_parity(policies, resources)


def test_double_flatten_nested_arrays_parity():
    """a[][] flattens the projected list: depth-1 arrays splice, their
    already-spliced children do not re-splice (code-review regression)."""
    rule = {
        "name": "nested",
        "match": {"any": [{"resources": {"kinds": ["Pod"]}}]},
        "preconditions": {
            "all": [
                {
                    "key": "{{ request.object.spec.a[][] }}",
                    "operator": "AllIn",
                    "value": [1, 2],
                }
            ]
        },
        "validate": {
            "message": "x",
            "deny": {"conditions": {"any": []}},
        },
    }
    policies = [make_policy("flat2", [rule])]
    resources = [
        pod("deep", spec={"a": [[[1, 2]]]}),     # a[][] -> [1,2] (list stays)
        pod("mixed", spec={"a": [[1], 2, [[3]]]}),  # -> [1, 2, 3]
        pod("scalar", spec={"a": [5]}),          # -> [5]
        pod("none", spec={}),
    ]
    check_parity(policies, resources)


def test_scalar_chain_glob_value_falls_back():
    policies = [
        make_policy(
            "glob-eq",
            [
                {
                    "name": "deny-host",
                    "match": {"any": [{"resources": {"kinds": ["Pod"]}}]},
                    "validate": {
                        "message": "x",
                        "deny": {
                            "conditions": {
                                "any": [
                                    {
                                        "key": "{{ request.object.spec.nodeName }}",
                                        "operator": "AnyIn",
                                        "value": ["master"],
                                    }
                                ]
                            }
                        },
                    },
                }
            ],
        )
    ]
    resources = [
        pod("wild", spec={"nodeName": "*"}),
        pod("hit", spec={"nodeName": "master"}),
        pod("miss", spec={"nodeName": "worker"}),
    ]
    check_parity(policies, resources)
