"""Device parity for depth-2 array-of-maps patterns and wildcard
metadata keys (the last two PSS host-fallback classes, VERDICT #2)."""

from kyverno_tpu.policies import load_pss_policies
from kyverno_tpu.policy.autogen import expand_policy
from kyverno_tpu.tpu.compiler import compile_policy_set

from test_tpu_parity import check_parity, make_policy, pod


HOST_PORTS_RULE = {
    "name": "host-ports",
    "match": {"any": [{"resources": {"kinds": ["Pod"]}}]},
    "validate": {
        "message": "host ports are disallowed",
        "pattern": {
            "spec": {
                "=(ephemeralContainers)": [{"=(ports)": [{"=(hostPort)": 0}]}],
                "=(initContainers)": [{"=(ports)": [{"=(hostPort)": 0}]}],
                "containers": [{"=(ports)": [{"=(hostPort)": 0}]}],
            }
        },
    },
}

APPARMOR_RULE = {
    "name": "app-armor",
    "match": {"any": [{"resources": {"kinds": ["Pod"]}}]},
    "validate": {
        "message": "apparmor profiles restricted",
        "pattern": {
            "=(metadata)": {
                "=(annotations)": {
                    "=(container.apparmor.security.beta.kubernetes.io/*)":
                        "runtime/default | localhost/*",
                }
            }
        },
    },
}


def ctr(name, ports=None):
    c = {"name": name, "image": "nginx"}
    if ports is not None:
        c["ports"] = ports
    return c


def test_pss_full_device_coverage():
    """VERDICT #2 done-criterion: every bundled PSS rule on device."""
    policies = [expand_policy(p) for p in load_pss_policies()]
    cps = compile_policy_set(policies)
    assert cps.coverage() == (66, 66), [
        (e.policy_name, e.rule_name, e.fallback_reason)
        for e in cps.rules if e.device_row is None
    ]


def test_nested_array_of_maps_parity():
    policies = [make_policy("host-ports", [HOST_PORTS_RULE])]
    resources = [
        # no ports at all
        pod("none", spec={"containers": [ctr("a")]}),
        # containerPort only (hostPort absent => equality anchor passes)
        pod("cport", spec={"containers": [ctr("a", [{"containerPort": 80}])]}),
        # hostPort 0 is allowed
        pod("zero", spec={"containers": [ctr("a", [{"containerPort": 80, "hostPort": 0}])]}),
        # hostPort violation
        pod("bad", spec={"containers": [ctr("a", [{"containerPort": 80, "hostPort": 8080}])]}),
        # violation in second port of second container
        pod("deep", spec={"containers": [
            ctr("a", [{"containerPort": 80}]),
            ctr("b", [{"containerPort": 81}, {"hostPort": 9090}]),
        ]}),
        # initContainers violation while main containers clean
        pod("init", spec={
            "containers": [ctr("a")],
            "initContainers": [ctr("i", [{"hostPort": 1}])],
        }),
        # empty ports array
        pod("empty-ports", spec={"containers": [ctr("a", [])]}),
        # ports not an array (schema violation -> pattern fail both paths)
        pod("scalar-ports", spec={"containers": [{"name": "a", "ports": "x"}]}),
    ]
    check_parity(policies, resources)


def _apod(name, annotations=None, labels=None):
    meta = {"name": name, "namespace": "default"}
    if annotations is not None:
        meta["annotations"] = annotations
    if labels is not None:
        meta["labels"] = labels
    return {"apiVersion": "v1", "kind": "Pod", "metadata": meta,
            "spec": {"containers": [{"name": "a", "image": "nginx"}]}}


def test_wildcard_metadata_key_parity():
    policies = [make_policy("apparmor", [APPARMOR_RULE])]
    resources = [
        # no annotations at all
        _apod("plain"),
        # unrelated annotation
        _apod("other", {"foo": "bar"}),
        # matching key, allowed value
        _apod("ok", {"container.apparmor.security.beta.kubernetes.io/app": "runtime/default"}),
        # matching key, localhost glob value
        _apod("lh", {"container.apparmor.security.beta.kubernetes.io/app": "localhost/prof-1"}),
        # matching key, denied value
        _apod("bad", {"container.apparmor.security.beta.kubernetes.io/app": "unconfined"}),
        # first matching key decides (oracle dict order)
        _apod("two", {
            "container.apparmor.security.beta.kubernetes.io/a": "unconfined",
            "container.apparmor.security.beta.kubernetes.io/b": "runtime/default",
        }),
        _apod("two-rev", {
            "container.apparmor.security.beta.kubernetes.io/a": "runtime/default",
            "container.apparmor.security.beta.kubernetes.io/b": "unconfined",
        }),
        # non-string annotation value disables expansion entirely
        _apod("nonstr", {"container.apparmor.security.beta.kubernetes.io/a": "unconfined",
                         "weird": 3}),
    ]
    check_parity(policies, resources)


def test_existence_anchor_depth_accounting():
    """An array-of-maps two levels below an existence anchor must fall
    back at COMPILE time, not crash the batch program at trace time
    (code-review finding #1); one level below works on device."""
    deep_rule = {
        "name": "deep",
        "match": {"any": [{"resources": {"kinds": ["Pod"]}}]},
        "validate": {
            "message": "x",
            "pattern": {"spec": {"^(containers)": [
                {"volumeMounts": [{"ports": [{"=(hostPort)": 0}]}]}
            ]}},
        },
    }
    cps = compile_policy_set([make_policy("deep", [deep_rule])])
    assert cps.coverage() == (0, 1)  # host fallback, not a trace crash

    ok_rule = {
        "name": "one-level",
        "match": {"any": [{"resources": {"kinds": ["Pod"]}}]},
        "validate": {
            "message": "x",
            "pattern": {"spec": {"^(containers)": [
                {"ports": [{"=(hostPort)": 0}]}
            ]}},
        },
    }
    policies = [make_policy("exist-nested", [ok_rule])]
    resources = [
        pod("ok", spec={"containers": [ctr("a", [{"hostPort": 0}])]}),
        pod("bad", spec={"containers": [ctr("a", [{"hostPort": 9}])]}),
        pod("none", spec={}),
    ]
    check_parity(policies, resources)


def test_wildcard_metadata_key_in_array_scope_falls_back():
    """The reference expands metadata wildcards at every map level,
    including array elements; the device cannot join that, so such
    rules must take the host path (code-review finding #2)."""
    rule = {
        "name": "vct-labels",
        "match": {"any": [{"resources": {"kinds": ["StatefulSet"]}}]},
        "validate": {
            "message": "x",
            "pattern": {"spec": {"volumeClaimTemplates": [
                {"metadata": {"labels": {"team.*": "eng"}}}
            ]}},
        },
    }
    cps = compile_policy_set([make_policy("vct", [rule])])
    assert cps.coverage() == (0, 1)
    policies = [make_policy("vct", [rule])]
    resources = [
        {"apiVersion": "apps/v1", "kind": "StatefulSet",
         "metadata": {"name": "s", "namespace": "default"},
         "spec": {"volumeClaimTemplates": [
             {"metadata": {"labels": {"team.core": "eng"}}}]}},
        {"apiVersion": "apps/v1", "kind": "StatefulSet",
         "metadata": {"name": "s2", "namespace": "default"},
         "spec": {"volumeClaimTemplates": [
             {"metadata": {"labels": {"team.core": "sales"}}}]}},
    ]
    check_parity(policies, resources)


def test_wildcard_key_in_labels_parity():
    rule = {
        "name": "team-label",
        "match": {"any": [{"resources": {"kinds": ["Pod"]}}]},
        "validate": {
            "message": "team labels must be kyverno-managed",
            "pattern": {"metadata": {"labels": {"team.*": "eng-?"}}},
        },
    }
    policies = [make_policy("labels", [rule])]
    resources = [
        _apod("hit", labels={"team.core": "eng-1"}),
        _apod("miss-val", labels={"team.core": "sales"}),
        # no label matches the glob: plain key stays literal & missing
        _apod("nolabel", labels={"app": "x"}),
        _apod("none"),
    ]
    check_parity(policies, resources)
