"""Device-vs-scalar parity: the TPU program must agree with the scalar
oracle on every (rule, resource) verdict."""

import numpy as np
import pytest

from kyverno_tpu.api.policy import ClusterPolicy
from kyverno_tpu.engine.engine import Engine as ScalarEngine
from kyverno_tpu.tpu.engine import (
    NOT_MATCHED,
    TpuEngine,
    VERDICT_NAMES,
    _scalar_rule_verdicts,
    build_scan_context,
)


def make_policy(name, rules):
    return ClusterPolicy.from_dict(
        {
            "apiVersion": "kyverno.io/v1",
            "kind": "ClusterPolicy",
            "metadata": {"name": name},
            "spec": {"rules": rules},
        }
    )


def scalar_table(policies, resources, ns_labels=None, operations=None):
    eng = ScalarEngine()
    rows = []
    for policy in policies:
        rule_names = [r.name for r in policy.get_rules() if r.has_validate()]
        per_rule = {rn: [] for rn in rule_names}
        for ci, res in enumerate(resources):
            kind = res.get("kind", "")
            ns = (res.get("metadata") or {}).get("namespace", "")
            key = (res.get("metadata") or {}).get("name", "") if kind == "Namespace" else ns
            nsl = (ns_labels or {}).get(key, {})
            op = (operations[ci] if operations else "") or ""
            pctx = build_scan_context(policy, res, nsl, op)
            verdicts = _scalar_rule_verdicts(eng, policy, pctx)
            for rn in rule_names:
                per_rule[rn].append(verdicts[rn])
        for rn in rule_names:
            rows.append(((policy.name, rn), per_rule[rn]))
    return rows


def check_parity(policies, resources, ns_labels=None, operations=None):
    eng = TpuEngine(policies)
    result = eng.scan(resources, ns_labels, operations)
    expected = scalar_table(policies, resources, ns_labels, operations)
    assert [r for r in result.rules] == [e[0] for e in expected]
    for row, ((pname, rname), exp) in enumerate(expected):
        got = result.verdicts[row].tolist()
        assert got == exp, (
            f"{pname}/{rname}: device={[VERDICT_NAMES[v] for v in got]} "
            f"scalar={[VERDICT_NAMES[v] for v in exp]}"
        )
    return eng


def pod(name="p", ns="default", spec=None, labels=None, kind="Pod"):
    return {
        "apiVersion": "v1",
        "kind": kind,
        "metadata": {"name": name, "namespace": ns, **({"labels": labels} if labels else {})},
        "spec": spec if spec is not None else {},
    }


HOST_NS_RULE = {
    "name": "host-namespaces",
    "match": {"any": [{"resources": {"kinds": ["Pod"]}}]},
    "validate": {
        "message": "host namespaces are disallowed",
        "pattern": {
            "spec": {"=(hostPID)": "false", "=(hostIPC)": "false", "=(hostNetwork)": "false"}
        },
    },
}


def test_equality_anchor_pattern():
    policies = [make_policy("disallow-host-namespaces", [HOST_NS_RULE])]
    resources = [
        pod(spec={}),                                  # keys absent -> pass
        pod(spec={"hostPID": True}),                   # true -> fail
        pod(spec={"hostNetwork": False}),              # false -> pass
        pod(spec={"hostIPC": "false"}),                # string false -> pass
        pod(kind="Service"),                           # not matched
        pod(spec={"hostPID": False, "hostIPC": True}),  # one bad -> fail
    ]
    eng = check_parity(policies, resources)
    assert eng.coverage() == (1, 1)


PRIVILEGED_RULE = {
    "name": "privileged-containers",
    "match": {"any": [{"resources": {"kinds": ["Pod"]}}]},
    "validate": {
        "message": "privileged mode is disallowed",
        "pattern": {
            "spec": {
                "=(ephemeralContainers)": [{"=(securityContext)": {"=(privileged)": "false"}}],
                "=(initContainers)": [{"=(securityContext)": {"=(privileged)": "false"}}],
                "containers": [{"=(securityContext)": {"=(privileged)": "false"}}],
            }
        },
    },
}


def test_array_of_maps_anchors():
    policies = [make_policy("disallow-privileged", [PRIVILEGED_RULE])]
    resources = [
        pod(spec={"containers": [{"name": "a"}]}),
        pod(spec={"containers": [{"name": "a", "securityContext": {"privileged": True}}]}),
        pod(spec={"containers": [{"name": "a", "securityContext": {"privileged": False}}]}),
        pod(spec={"containers": [{"name": "a"}],
                  "initContainers": [{"name": "b", "securityContext": {"privileged": True}}]}),
        pod(spec={"containers": []}),
        pod(spec={}),  # containers missing -> fail (plain key)
        pod(spec={"containers": [{"securityContext": {}}]}),
        pod(spec={"containers": [{"securityContext": {"privileged": "true"}}]}),
    ]
    check_parity(policies, resources)


SECCOMP_RULE = {
    "name": "seccomp",
    "match": {"any": [{"resources": {"kinds": ["Pod"]}}]},
    "validate": {
        "message": "custom seccomp profiles are disallowed",
        "pattern": {
            "spec": {
                "=(securityContext)": {"=(seccompProfile)": {"=(type)": "RuntimeDefault | Localhost"}},
                "containers": [
                    {"=(securityContext)": {"=(seccompProfile)": {"=(type)": "RuntimeDefault | Localhost"}}}
                ],
            }
        },
    },
}


def test_or_alternatives_leaf():
    policies = [make_policy("restrict-seccomp", [SECCOMP_RULE])]
    resources = [
        pod(spec={"containers": [{"name": "a"}]}),
        pod(spec={"securityContext": {"seccompProfile": {"type": "Unconfined"}},
                  "containers": [{"name": "a"}]}),
        pod(spec={"securityContext": {"seccompProfile": {"type": "RuntimeDefault"}},
                  "containers": [{"name": "a"}]}),
        pod(spec={"containers": [{"securityContext": {"seccompProfile": {"type": "Localhost"}}}]}),
        pod(spec={"containers": [{"securityContext": {"seccompProfile": {"type": "Bad"}}}]}),
    ]
    check_parity(policies, resources)


CAPABILITIES_DENY_RULE = {
    "name": "adding-capabilities",
    "match": {"any": [{"resources": {"kinds": ["Pod"]}}]},
    "preconditions": {
        "all": [
            {"key": "{{ request.operation || 'BACKGROUND' }}", "operator": "NotEquals", "value": "DELETE"}
        ]
    },
    "validate": {
        "message": "capabilities beyond the allowed list are disallowed",
        "deny": {
            "conditions": {
                "all": [
                    {
                        "key": "{{ request.object.spec.[ephemeralContainers, initContainers, containers][].securityContext.capabilities.add[] }}",
                        "operator": "AnyNotIn",
                        "value": ["AUDIT_WRITE", "CHOWN", "KILL", "NET_BIND_SERVICE", "SETUID"],
                    }
                ]
            }
        },
    },
}


def test_deny_multiselect_capabilities():
    policies = [make_policy("disallow-capabilities", [CAPABILITIES_DENY_RULE])]
    resources = [
        pod(spec={"containers": [{"name": "a"}]}),
        pod(spec={"containers": [{"securityContext": {"capabilities": {"add": ["CHOWN"]}}}]}),
        pod(spec={"containers": [{"securityContext": {"capabilities": {"add": ["SYS_ADMIN"]}}}]}),
        pod(spec={
            "containers": [{"securityContext": {"capabilities": {"add": ["KILL"]}}}],
            "initContainers": [{"securityContext": {"capabilities": {"add": ["NET_RAW"]}}}],
        }),
        pod(spec={"containers": [{"securityContext": {"capabilities": {}}}]}),
    ]
    check_parity(policies, resources, operations=["", "", "", "", "DELETE"])


VOLUME_TYPES_RULE = {
    "name": "restricted-volumes",
    "match": {"any": [{"resources": {"kinds": ["Pod"]}}]},
    "validate": {
        "message": "only allowed volume types",
        "deny": {
            "conditions": {
                "all": [
                    {
                        "key": "{{ request.object.spec.volumes[].keys(@)[] || '' }}",
                        "operator": "AnyNotIn",
                        "value": ["name", "configMap", "secret", "emptyDir",
                                  "projected", "persistentVolumeClaim", "downwardAPI",
                                  "csi", "ephemeral", ""],
                    }
                ]
            }
        },
    },
}


def test_deny_keys_projection():
    policies = [make_policy("restrict-volume-types", [VOLUME_TYPES_RULE])]
    resources = [
        pod(spec={}),
        pod(spec={"volumes": []}),
        pod(spec={"volumes": [{"name": "v", "configMap": {"name": "c"}}]}),
        pod(spec={"volumes": [{"name": "v", "hostPath": {"path": "/"}}]}),
        pod(spec={"volumes": [{"name": "v", "secret": {}}, {"name": "w", "nfs": {}}]}),
    ]
    check_parity(policies, resources)


def test_negation_and_anypattern():
    rules = [
        {
            "name": "no-hostpath",
            "match": {"any": [{"resources": {"kinds": ["Pod"]}}]},
            "validate": {
                "pattern": {"spec": {"=(volumes)": [{"X(hostPath)": "null"}]}},
            },
        },
        {
            "name": "run-as-nonroot",
            "match": {"any": [{"resources": {"kinds": ["Pod"]}}]},
            "validate": {
                "anyPattern": [
                    {"spec": {"securityContext": {"runAsNonRoot": True},
                              "containers": [{"=(securityContext)": {"=(runAsNonRoot)": True}}]}},
                    {"spec": {"containers": [{"securityContext": {"runAsNonRoot": True}}]}},
                ],
            },
        },
    ]
    policies = [make_policy("p", rules)]
    resources = [
        pod(spec={"volumes": [{"name": "v", "emptyDir": {}}],
                  "containers": [{"name": "a"}]}),
        pod(spec={"volumes": [{"name": "v", "hostPath": {"path": "/"}}],
                  "containers": [{"securityContext": {"runAsNonRoot": True}}]}),
        pod(spec={"securityContext": {"runAsNonRoot": True},
                  "containers": [{"name": "a"}]}),
        pod(spec={"securityContext": {"runAsNonRoot": True},
                  "containers": [{"securityContext": {"runAsNonRoot": False}}]}),
        pod(spec={"containers": [{"securityContext": {"runAsNonRoot": True}},
                                 {"securityContext": {"runAsNonRoot": True}}]}),
    ]
    check_parity(policies, resources)


def test_match_exclude_selectors_namespaces():
    rules = [
        {
            "name": "ns-gate",
            "match": {"any": [{"resources": {"kinds": ["Pod"], "namespaces": ["prod-*"],
                                             "selector": {"matchLabels": {"app": "web"}}}}]},
            "exclude": {"any": [{"resources": {"names": ["skip-me"]}}]},
            "validate": {"pattern": {"spec": {"=(hostNetwork)": "false"}}},
        }
    ]
    policies = [make_policy("gated", rules)]
    resources = [
        pod(ns="prod-eu", labels={"app": "web"}, spec={"hostNetwork": True}),
        pod(ns="prod-eu", labels={"app": "db"}, spec={"hostNetwork": True}),
        pod(ns="dev", labels={"app": "web"}, spec={"hostNetwork": True}),
        pod(name="skip-me", ns="prod-us", labels={"app": "web"}, spec={"hostNetwork": True}),
        pod(ns="prod-us", labels={"app": "web"}, spec={}),
    ]
    check_parity(policies, resources)


def test_glob_leaf_operand():
    rules = [
        {
            "name": "image-registry",
            "match": {"any": [{"resources": {"kinds": ["Pod"]}}]},
            "validate": {
                "pattern": {"spec": {"containers": [{"image": "registry.corp.io/* | docker.io/*"}]}},
            },
        }
    ]
    policies = [make_policy("images", rules)]
    resources = [
        pod(spec={"containers": [{"image": "registry.corp.io/app:1"}]}),
        pod(spec={"containers": [{"image": "evil.io/app"}]}),
        pod(spec={"containers": [{"image": "docker.io/nginx"},
                                 {"image": "registry.corp.io/x"}]}),
        pod(spec={"containers": [{"image": "docker.io/nginx"}, {"image": "quay.io/x"}]}),
        pod(spec={"containers": [{"name": "no-image"}]}),
    ]
    check_parity(policies, resources)


def test_operator_leaves():
    rules = [
        {
            "name": "limits",
            "match": {"any": [{"resources": {"kinds": ["Pod"]}}]},
            "validate": {
                "pattern": {
                    "spec": {
                        "containers": [
                            {"resources": {"limits": {"memory": "<=1Gi", "cpu": "<2"}}}
                        ]
                    }
                },
            },
        }
    ]
    policies = [make_policy("limits", rules)]
    resources = [
        pod(spec={"containers": [{"resources": {"limits": {"memory": "512Mi", "cpu": "500m"}}}]}),
        pod(spec={"containers": [{"resources": {"limits": {"memory": "2Gi", "cpu": "1"}}}]}),
        pod(spec={"containers": [{"resources": {"limits": {"memory": "1Gi", "cpu": 2}}}]}),
        pod(spec={"containers": [{"resources": {"limits": {"memory": "1024Mi", "cpu": "1.5"}}}]}),
        pod(spec={"containers": [{"name": "a"}]}),
    ]
    check_parity(policies, resources)


def test_host_fallback_rules_complete():
    rules = [
        {
            "name": "foreach-rule",  # unsupported on device
            "match": {"any": [{"resources": {"kinds": ["Pod"]}}]},
            "validate": {
                "foreach": [
                    {"list": "request.object.spec.containers",
                     "pattern": {"image": "docker.io/*"}}
                ],
            },
        },
        HOST_NS_RULE,
    ]
    policies = [make_policy("mixed", rules)]
    resources = [
        pod(spec={"containers": [{"image": "docker.io/a"}], "hostPID": True}),
        pod(spec={"containers": [{"image": "evil.io/a"}]}),
    ]
    eng = check_parity(policies, resources)
    assert eng.coverage() == (1, 2)


def test_engine_buckets_batch_shapes(monkeypatch, no_verdict_cache):
    """Two odd-sized batches must reuse one compiled shape (SURVEY §7
    recompilation churn: bucketing lives in the engine, not in caller
    convention)."""
    from kyverno_tpu.tpu.engine import TpuEngine

    pol = ClusterPolicy.from_dict({
        "apiVersion": "kyverno.io/v1", "kind": "ClusterPolicy",
        "metadata": {"name": "b"},
        "spec": {"rules": [{
            "name": "r",
            "match": {"any": [{"resources": {"kinds": ["Pod"]}}]},
            "validate": {"pattern": {"metadata": {"name": "?*"}}},
        }]},
    })
    eng = TpuEngine([pol])
    shapes = []
    real_fn = eng.cps.device_fn()

    def spying(batch):
        shapes.append(batch["norm_hi"].shape[0])
        return real_fn(batch)

    monkeypatch.setattr(eng.cps, "device_fn", lambda: spying)

    def mk(i):
        return {"apiVersion": "v1", "kind": "Pod",
                "metadata": {"name": f"x{i}", "namespace": "d"}, "spec": {}}

    r1 = eng.scan([mk(i) for i in range(3)])
    r2 = eng.scan([mk(i) for i in range(13)])
    assert shapes == [16, 16]  # both bucket to MIN_BUCKET
    assert r1.verdicts.shape[1] == 3 and r2.verdicts.shape[1] == 13
    r3 = eng.scan([mk(i) for i in range(17)])
    assert shapes[-1] == 32 and r3.verdicts.shape[1] == 17


def test_static_context_folding():
    """Literal `variable` context entries constant-fold at compile so
    the rule lowers to device; jmesPath-only (request-reading) entries
    must NOT fold — an empty compile context would bake their default
    arm in as a wrong constant."""
    from kyverno_tpu.api.policy import ClusterPolicy
    from kyverno_tpu.tpu.compiler import compile_policy_set

    def policy(context, conditions):
        return ClusterPolicy.from_dict({
            "apiVersion": "kyverno.io/v1", "kind": "ClusterPolicy",
            "metadata": {"name": "p"},
            "spec": {"rules": [{
                "name": "r", "context": context,
                "match": {"any": [{"resources": {"kinds": ["Pod"]}}]},
                "validate": {"message": "m",
                             "deny": {"conditions": {"any": conditions}}},
            }]}})

    static = policy(
        [{"name": "maxmem", "variable": {"value": "1Gi"}}],
        [{"key": "{{ request.object.spec.mem }}", "operator": "GreaterThan",
          "value": "{{ maxmem }}"}])
    cps = compile_policy_set([static])
    assert cps.coverage() == (1, 1), cps.rules[0].fallback_reason

    # request-reading jmesPath entries now lower by INLINING the
    # expression (with its default) into the references, so per-request
    # values come from the resource rows, never from a baked constant
    dynamic = policy(
        [{"name": "replicas", "variable": {
            "jmesPath": "request.object.spec.replicas", "default": 1}}],
        [{"key": "{{ replicas }}", "operator": "GreaterThan", "value": 10}])
    cps = compile_policy_set([dynamic])
    assert cps.coverage() == (1, 1), cps.rules[0].fallback_reason
    from kyverno_tpu.tpu.engine import TpuEngine as _Eng

    deng = _Eng([dynamic])
    dres = deng.scan([
        {"apiVersion": "v1", "kind": "Pod",
         "metadata": {"name": "a"}, "spec": {"replicas": 20}},
        {"apiVersion": "v1", "kind": "Pod",
         "metadata": {"name": "b"}, "spec": {"replicas": 5}},
        {"apiVersion": "v1", "kind": "Pod",
         "metadata": {"name": "c"}, "spec": {}},  # default arm: 1 -> pass
    ])
    assert [int(dres.verdicts[0, i]) for i in range(3)] == [2, 0, 0]
    # a truly dynamic entry (apiCall) lowers via a host-resolved
    # operand slot: the condition compares on device, the value loads
    # through the real loaders per batch
    apicall = policy(
        [{"name": "pods", "apiCall": {"urlPath": "/api/v1/pods"}}],
        [{"key": "{{ pods }}", "operator": "Equals", "value": 1}])
    cps = compile_policy_set([apicall])
    assert cps.coverage() == (1, 1)
    assert len(cps.dyn_slots) == 1
    # ... but an UNREFERENCED dynamic entry drops away (deferred
    # loading never materializes it)
    unused = policy(
        [{"name": "pods", "apiCall": {"urlPath": "/api/v1/pods"}}],
        [{"key": "{{ request.object.spec.x }}", "operator": "Equals",
          "value": 1}])
    assert compile_policy_set([unused]).coverage() == (1, 1)

    # folded constants evaluate correctly end to end
    from kyverno_tpu.tpu.engine import TpuEngine

    eng = TpuEngine([static])
    res = eng.scan([
        {"apiVersion": "v1", "kind": "Pod",
         "metadata": {"name": "big"}, "spec": {"mem": "2Gi"}},
        {"apiVersion": "v1", "kind": "Pod",
         "metadata": {"name": "ok"}, "spec": {"mem": "512Mi"}},
    ])
    assert res.verdicts[0, 0] == 2 and res.verdicts[0, 1] == 0  # FAIL, PASS


def test_literal_key_condition_constant_folds():
    """Non-variable condition keys (e.g. folded constants) lower as
    compile-time constants via the scalar oracle."""
    from kyverno_tpu.api.policy import ClusterPolicy
    from kyverno_tpu.tpu.engine import TpuEngine

    p = ClusterPolicy.from_dict({
        "apiVersion": "kyverno.io/v1", "kind": "ClusterPolicy",
        "metadata": {"name": "p"},
        "spec": {"rules": [{
            "name": "r",
            "match": {"any": [{"resources": {"kinds": ["Pod"]}}]},
            "validate": {"message": "m", "deny": {"conditions": {"all": [
                {"key": "prod", "operator": "Equals", "value": "prod"},
                {"key": "{{ request.object.spec.bad }}", "operator": "Equals",
                 "value": True},
            ]}}},
        }]}})
    eng = TpuEngine(p if isinstance(p, list) else [p])
    assert eng.coverage() == (1, 1), eng.cps.rules[0].fallback_reason
    res = eng.scan([
        {"apiVersion": "v1", "kind": "Pod", "metadata": {"name": "a"},
         "spec": {"bad": True}},
        {"apiVersion": "v1", "kind": "Pod", "metadata": {"name": "b"},
         "spec": {"bad": False}},
    ])
    assert res.verdicts[0, 0] == 2 and res.verdicts[0, 1] == 0


def test_deprecated_in_notin_device_parity():
    """Deprecated In/NotIn lower to device for scalar-chain keys with
    list values; verdicts must match the scalar engine exactly,
    including the strict list-key semantics (in.go:35-43: non-string
    elements force false for both directions)."""
    from kyverno_tpu.api.policy import ClusterPolicy
    from kyverno_tpu.engine.engine import Engine
    from kyverno_tpu.tpu.engine import TpuEngine, build_scan_context

    def policy(op, value):
        return ClusterPolicy.from_dict({
            "apiVersion": "kyverno.io/v1", "kind": "ClusterPolicy",
            "metadata": {"name": "p"},
            "spec": {"rules": [{
                "name": "r",
                "match": {"any": [{"resources": {"kinds": ["Pod"]}}]},
                "validate": {"message": "m", "deny": {"conditions": {"any": [
                    {"key": "{{ request.object.spec.val }}",
                     "operator": op, "value": value}]}}},
            }]}})

    pods = [
        {"apiVersion": "v1", "kind": "Pod", "metadata": {"name": "scalar-hit"},
         "spec": {"val": "a"}},
        {"apiVersion": "v1", "kind": "Pod", "metadata": {"name": "scalar-miss"},
         "spec": {"val": "z"}},
        {"apiVersion": "v1", "kind": "Pod", "metadata": {"name": "num-key"},
         "spec": {"val": 2}},
        {"apiVersion": "v1", "kind": "Pod", "metadata": {"name": "missing"},
         "spec": {}},
        {"apiVersion": "v1", "kind": "Pod", "metadata": {"name": "map-key"},
         "spec": {"val": {"m": 1}}},
        {"apiVersion": "v1", "kind": "Pod", "metadata": {"name": "list-all-in"},
         "spec": {"val": ["a", "b"]}},
        {"apiVersion": "v1", "kind": "Pod", "metadata": {"name": "list-partial"},
         "spec": {"val": ["a", "z"]}},
        {"apiVersion": "v1", "kind": "Pod", "metadata": {"name": "list-nonstr"},
         "spec": {"val": ["a", 2]}},
        {"apiVersion": "v1", "kind": "Pod", "metadata": {"name": "list-empty"},
         "spec": {"val": []}},
    ]
    scalar = Engine()
    code = {"pass": 0, "skip": 1, "fail": 2, "error": 4}
    for op in ("In", "NotIn"):
        for value in (["a", "b", "2"], ["z"]):
            p = policy(op, value)
            eng = TpuEngine([p])
            assert eng.coverage() == (1, 1), eng.cps.rules[0].fallback_reason
            res = eng.scan(pods)
            for ci, pod in enumerate(pods):
                resp = scalar.validate(build_scan_context(p, pod, {}))
                want = code[resp.policy_response.rules[0].status]
                got = int(res.verdicts[0, ci])
                assert got == want, (op, value, pod["metadata"]["name"], got, want)


def test_deprecated_in_operation_key_and_nonstring_values():
    """Regressions: {{request.operation}} In [...] must not invert on
    device; non-string literal values force host fallback (in.go
    invalidType vs device sprint-coercion)."""
    from kyverno_tpu.api.policy import ClusterPolicy
    from kyverno_tpu.tpu.engine import TpuEngine

    op_pol = ClusterPolicy.from_dict({
        "apiVersion": "kyverno.io/v1", "kind": "ClusterPolicy",
        "metadata": {"name": "p"},
        "spec": {"rules": [{
            "name": "r",
            "match": {"any": [{"resources": {"kinds": ["Pod"]}}]},
            "preconditions": {"all": [{
                "key": "{{ request.operation }}", "operator": "In",
                "value": ["CREATE", "UPDATE"]}]},
            "validate": {"message": "m",
                         "pattern": {"metadata": {"name": "allowed"}}},
        }]}})
    eng = TpuEngine([op_pol])
    assert eng.coverage() == (1, 1), eng.cps.rules[0].fallback_reason
    pod = {"apiVersion": "v1", "kind": "Pod",
           "metadata": {"name": "other"}, "spec": {}}
    res = eng.scan([pod], operations=["CREATE"])
    assert int(res.verdicts[0, 0]) == 2  # precondition held -> pattern FAIL
    res = eng.scan([pod], operations=["DELETE"])
    assert int(res.verdicts[0, 0]) == 1  # precondition false -> SKIP

    mixed = ClusterPolicy.from_dict({
        "apiVersion": "kyverno.io/v1", "kind": "ClusterPolicy",
        "metadata": {"name": "p2"},
        "spec": {"rules": [{
            "name": "r",
            "match": {"any": [{"resources": {"kinds": ["Pod"]}}]},
            "validate": {"message": "m", "deny": {"conditions": {"any": [{
                "key": "{{ request.object.spec.val }}", "operator": "In",
                "value": ["a", 2]}]}}},
        }]}})
    eng = TpuEngine([mixed])
    assert eng.coverage() == (0, 1)  # non-string values stay host


def test_wildcard_label_selector_device_parity():
    """matchLabels with glob keys/values lower to device via the label
    byte lanes; verdicts match the scalar engine, including the
    '0'-substitution fallback when nothing glob-matches."""
    from kyverno_tpu.api.policy import ClusterPolicy
    from kyverno_tpu.engine.engine import Engine
    from kyverno_tpu.tpu.engine import TpuEngine, build_scan_context

    policy = ClusterPolicy.from_dict({
        "apiVersion": "kyverno.io/v1", "kind": "ClusterPolicy",
        "metadata": {"name": "wild"},
        "spec": {"rules": [{
            "name": "r",
            "match": {"any": [{"resources": {
                "kinds": ["Pod"],
                "selector": {"matchLabels": {"app*": "prod-?"}}}}]},
            "validate": {"message": "m",
                         "pattern": {"metadata": {"name": "!bad"}}},
        }]}})
    pods = [
        {"apiVersion": "v1", "kind": "Pod",
         "metadata": {"name": "bad", "labels": {"apptier": "prod-1"}},
         "spec": {}},
        {"apiVersion": "v1", "kind": "Pod",
         "metadata": {"name": "ok", "labels": {"apptier": "prod-1"}},
         "spec": {}},
        {"apiVersion": "v1", "kind": "Pod",
         "metadata": {"name": "bad", "labels": {"apptier": "staging"}},
         "spec": {}},
        {"apiVersion": "v1", "kind": "Pod",
         "metadata": {"name": "bad", "labels": {"other": "prod-1"}},
         "spec": {}},
        {"apiVersion": "v1", "kind": "Pod",
         "metadata": {"name": "bad", "labels": {"app0": "prod-0"}},
         "spec": {}},  # the '0'-substituted exact pair
        {"apiVersion": "v1", "kind": "Pod", "metadata": {"name": "bad"},
         "spec": {}},
    ]
    eng = TpuEngine([policy])
    assert eng.coverage() == (1, 1), eng.cps.rules[0].fallback_reason
    res = eng.scan(pods)
    code = {"pass": 0, "skip": 1, "fail": 2, "error": 4}
    scalar = Engine()
    for ci, pod in enumerate(pods):
        resp = scalar.validate(build_scan_context(policy, pod, {}))
        want = code[resp.policy_response.rules[0].status] \
            if resp.policy_response.rules else 3
        assert int(res.verdicts[0, ci]) == want, (ci, int(res.verdicts[0, ci]), want)


def test_wildcard_selector_collision_and_invalid_substitution_stay_host():
    """Dict-collision and resource-dependent-validity cases cannot
    lower soundly: they must fall back to host, not silently diverge."""
    from kyverno_tpu.api.policy import ClusterPolicy
    from kyverno_tpu.tpu.engine import TpuEngine

    def pol(match_labels):
        return ClusterPolicy.from_dict({
            "apiVersion": "kyverno.io/v1", "kind": "ClusterPolicy",
            "metadata": {"name": "p"},
            "spec": {"rules": [{
                "name": "r",
                "match": {"any": [{"resources": {
                    "kinds": ["Pod"],
                    "selector": {"matchLabels": match_labels}}}]},
                "validate": {"message": "m",
                             "pattern": {"metadata": {"name": "?*"}}}}]}})

    # wildcard key can expand onto the literal "app" entry -> host
    assert TpuEngine([pol({"app": "x", "app*": "y*"})]).coverage() == (0, 1)
    # two wildcard entries can collide with each other -> host
    assert TpuEngine([pol({"a*": "x", "ap*": "y"})]).coverage() == (0, 1)
    # '0'-substitution of a 64+ char glob key is invalid label syntax,
    # but a real label could substitute validly -> host, not constant
    long_key = "k" * 70 + "*"
    assert TpuEngine([pol({long_key: "v"})]).coverage() == (0, 1)


def test_wildcard_selector_invalid_label_syntax_goes_host():
    """A resource carrying a syntactically invalid label key makes the
    scalar engine ERROR the wildcard selector ('failed to parse
    selector' -> not matched) — on device the resource must take the
    HOST path, not glob-match (parity via fallback)."""
    from kyverno_tpu.api.policy import ClusterPolicy
    from kyverno_tpu.engine.engine import Engine
    from kyverno_tpu.tpu.engine import TpuEngine, build_scan_context

    policy = ClusterPolicy.from_dict({
        "apiVersion": "kyverno.io/v1", "kind": "ClusterPolicy",
        "metadata": {"name": "wild"},
        "spec": {"rules": [{
            "name": "r",
            "match": {"any": [{"resources": {
                "kinds": ["Pod"],
                "selector": {"matchLabels": {"app*": "x"}}}}]},
            "validate": {"message": "m",
                         "pattern": {"metadata": {"name": "!bad"}}},
        }]}})
    pods = [
        {"apiVersion": "v1", "kind": "Pod",
         "metadata": {"name": "bad", "labels": {"app-": "x"}}, "spec": {}},
        {"apiVersion": "v1", "kind": "Pod",
         "metadata": {"name": "bad", "labels": {"apptier": "x"}}, "spec": {}},
    ]
    eng = TpuEngine([policy])
    assert eng.coverage() == (1, 1)
    res = eng.scan(pods)
    code = {"pass": 0, "skip": 1, "fail": 2, "error": 4}
    scalar = Engine()
    for ci, pod in enumerate(pods):
        resp = scalar.validate(build_scan_context(policy, pod, {}))
        want = code[resp.policy_response.rules[0].status] \
            if resp.policy_response.rules else 3
        assert int(res.verdicts[0, ci]) == want, (ci, int(res.verdicts[0, ci]), want)


def test_value_only_wildcard_multi_entries_lower():
    """Multiple value-only glob entries keep literal keys — no dict
    collision is possible, so they lower and match scalar verdicts."""
    from kyverno_tpu.api.policy import ClusterPolicy
    from kyverno_tpu.engine.engine import Engine
    from kyverno_tpu.tpu.engine import TpuEngine, build_scan_context

    policy = ClusterPolicy.from_dict({
        "apiVersion": "kyverno.io/v1", "kind": "ClusterPolicy",
        "metadata": {"name": "wild2"},
        "spec": {"rules": [{
            "name": "r",
            "match": {"any": [{"resources": {
                "kinds": ["Pod"],
                "selector": {"matchLabels": {"app": "prod-*",
                                             "tier": "web-?"}}}}]},
            "validate": {"message": "m",
                         "pattern": {"metadata": {"name": "!bad"}}},
        }]}})
    eng = TpuEngine([policy])
    assert eng.coverage() == (1, 1), eng.cps.rules[0].fallback_reason
    pods = [
        {"apiVersion": "v1", "kind": "Pod",
         "metadata": {"name": "bad",
                      "labels": {"app": "prod-1", "tier": "web-a"}}, "spec": {}},
        {"apiVersion": "v1", "kind": "Pod",
         "metadata": {"name": "bad",
                      "labels": {"app": "prod-1", "tier": "webXa"}}, "spec": {}},
        {"apiVersion": "v1", "kind": "Pod",
         "metadata": {"name": "bad", "labels": {"app": "prod-1"}}, "spec": {}},
    ]
    res = eng.scan(pods)
    code = {"pass": 0, "skip": 1, "fail": 2, "error": 4}
    scalar = Engine()
    for ci, pod in enumerate(pods):
        resp = scalar.validate(build_scan_context(policy, pod, {}))
        want = code[resp.policy_response.rules[0].status] \
            if resp.policy_response.rules else 3
        assert int(res.verdicts[0, ci]) == want, (ci, int(res.verdicts[0, ci]), want)


def test_userinfo_key_membership_parity():
    """{{ request.userInfo.groups }} membership conditions lower to the
    RBAC identity lanes; device verdicts match the scalar oracle for
    present, absent and empty identities."""
    from kyverno_tpu.api.policy import ClusterPolicy
    from kyverno_tpu.engine.engine import Engine
    from kyverno_tpu.engine.match import RequestInfo
    from kyverno_tpu.tpu.engine import (TpuEngine, _scalar_rule_verdicts,
                                        build_scan_context)

    pol = ClusterPolicy.from_dict({
        "apiVersion": "kyverno.io/v1", "kind": "ClusterPolicy",
        "metadata": {"name": "p"},
        "spec": {"rules": [{
            "name": "r",
            "match": {"any": [{"resources": {"kinds": ["Role"]}}]},
            "preconditions": {"all": [
                {"key": "{{ request.operation }}", "operator": "AnyIn",
                 "value": ["UPDATE", "DELETE"]},
                {"key": "{{ request.userInfo.groups }}",
                 "operator": "AllNotIn", "value": ["system:masters"]}]},
            "validate": {"message": "m", "deny": {}},
        }]}})
    eng = TpuEngine([pol])
    assert eng.cps.coverage() == (1, 1), eng.cps.rules[0].fallback_reason
    scal = Engine()
    role = {"apiVersion": "rbac.authorization.k8s.io/v1", "kind": "Role",
            "metadata": {"name": "r1", "namespace": "d"}}
    cases = [
        ("UPDATE", RequestInfo(username="u", groups=["system:masters", "x"])),
        ("UPDATE", RequestInfo(username="u", groups=["devs"])),
        ("CREATE", RequestInfo(username="u", groups=["devs"])),
        ("DELETE", RequestInfo(username="u", groups=[])),
        ("UPDATE", None),
    ]
    res = eng.scan([role] * len(cases), {},
                   operations=[c[0] for c in cases],
                   admission_infos=[c[1] for c in cases])
    for i, (op, info) in enumerate(cases):
        pctx = build_scan_context(pol, role, {}, op, info)
        sv = _scalar_rule_verdicts(scal, pol, pctx).get("r")
        assert int(res.verdicts[0, i]) == sv, (i, op, info)


def test_not_null_defaults_loader_semantics_parity():
    """Inlined context-variable defaults use not_null() — the loader's
    null-only semantics, NOT jmespath || falsiness: an empty-string
    key keeps the empty string. Literal, chain and numeric defaults."""
    from kyverno_tpu.api.policy import ClusterPolicy
    from kyverno_tpu.engine.engine import Engine
    from kyverno_tpu.tpu.engine import (TpuEngine, _scalar_rule_verdicts,
                                        build_scan_context)

    def mk(context, conds):
        return ClusterPolicy.from_dict({
            "apiVersion": "kyverno.io/v1", "kind": "ClusterPolicy",
            "metadata": {"name": "p"},
            "spec": {"rules": [{
                "name": "r", "context": context,
                "match": {"any": [{"resources": {"kinds": ["Pod"]}}]},
                "validate": {"message": "m", "deny": {"conditions": conds}},
            }]}})

    scal = Engine()
    pods = [
        {"apiVersion": "v1", "kind": "Pod", "metadata": {"name": "a"}},
        {"apiVersion": "v1", "kind": "Pod", "metadata": {"name": "example"}},
        {"apiVersion": "v1", "kind": "Pod",
         "metadata": {"name": "b", "generateName": "x"}},
        {"apiVersion": "v1", "kind": "Pod",
         "metadata": {"name": "c", "generateName": ""}},
    ]
    policies = [
        mk([{"name": "n", "variable": {
            "jmesPath": "request.object.metadata.generateName",
            "default": "example"}}],
           [{"key": "{{ n }}", "operator": "NotEquals", "value": "example"}]),
        mk([{"name": "n", "variable": {
            "jmesPath": "request.object.metadata.generateName",
            "default": "{{ request.object.metadata.name }}"}}],
           [{"key": "{{ n }}", "operator": "NotEquals", "value": "example"}]),
    ]
    for pol in policies:
        eng = TpuEngine([pol])
        assert eng.cps.coverage() == (1, 1), eng.cps.rules[0].fallback_reason
        res = eng.scan(pods, {})
        for i, r in enumerate(pods):
            pctx = build_scan_context(pol, r, {})
            sv = _scalar_rule_verdicts(scal, pol, pctx).get("r")
            assert int(res.verdicts[0, i]) == sv, (pol.name, i)
