"""Full-set parity fuzz: the autogen-expanded 18-policy PSS library vs
randomized pod/controller resources, device verdicts == scalar oracle."""

import random

import pytest

from kyverno_tpu.policies import load_pss_policies
from kyverno_tpu.policy.autogen import expand_policy

from test_tpu_parity import check_parity


def _sec_ctx(rng, pod_level=False):
    out = {}
    if rng.random() < 0.3:
        out["privileged"] = rng.choice([True, False, "false", "true"])
    if rng.random() < 0.3:
        out["allowPrivilegeEscalation"] = rng.choice([True, False])
    if rng.random() < 0.3:
        out["runAsNonRoot"] = rng.choice([True, False])
    if rng.random() < 0.3:
        out["runAsUser"] = rng.choice([0, 1000, "0", 65535])
    if rng.random() < 0.2:
        out["runAsGroup"] = rng.choice([0, 3000])
    if rng.random() < 0.25:
        out["seccompProfile"] = {"type": rng.choice(
            ["RuntimeDefault", "Localhost", "Unconfined", None])}
    if rng.random() < 0.2:
        out["seLinuxOptions"] = {
            k: v for k, v in {
                "type": rng.choice(["container_t", "spc_t", None]),
                "user": rng.choice(["system_u", None]),
                "role": rng.choice(["system_r", None]),
            }.items() if v is not None
        }
    if rng.random() < 0.2:
        out["capabilities"] = {
            rng.choice(["add", "drop"]): rng.sample(
                ["ALL", "CHOWN", "SYS_ADMIN", "KILL", "NET_RAW", "NET_BIND_SERVICE"],
                k=rng.randint(0, 3),
            )
        }
    if not pod_level and rng.random() < 0.2:
        out["procMount"] = rng.choice(["Default", "Unmasked"])
    if not pod_level and rng.random() < 0.15:
        out["windowsOptions"] = {"hostProcess": rng.choice([True, False])}
    if pod_level and rng.random() < 0.2:
        out["sysctls"] = [{"name": rng.choice(
            ["kernel.shm_rmid_forced", "net.core.somaxconn", "net.ipv4.tcp_syncookies"]),
            "value": "1"}]
    if pod_level and rng.random() < 0.2:
        out["supplementalGroups"] = rng.sample([0, 1000, 2000], k=rng.randint(1, 2))
    if pod_level and rng.random() < 0.2:
        out["fsGroup"] = rng.choice([0, 2000])
    return out


def _container(rng, name):
    c = {"name": name, "image": rng.choice(["nginx", "docker.io/redis:7", "evil.io/x"])}
    sc = _sec_ctx(rng)
    if sc or rng.random() < 0.3:
        c["securityContext"] = sc
    if rng.random() < 0.25:
        ports = [{"containerPort": 80}]
        if rng.random() < 0.5:
            ports[0]["hostPort"] = rng.choice([0, 8080])
        c["ports"] = ports
    return c


def _volume(rng, i):
    kind = rng.choice(["emptyDir", "configMap", "hostPath", "secret", "nfs"])
    body = {"path": "/"} if kind == "hostPath" else {}
    return {"name": f"v{i}", kind: body}


def _pod_spec(rng):
    spec = {"containers": [_container(rng, f"c{i}") for i in range(rng.randint(1, 3))]}
    if rng.random() < 0.3:
        spec["initContainers"] = [_container(rng, "init")]
    if rng.random() < 0.15:
        spec["ephemeralContainers"] = [_container(rng, "dbg")]
    for key in ("hostPID", "hostIPC", "hostNetwork"):
        if rng.random() < 0.2:
            spec[key] = rng.choice([True, False])
    if rng.random() < 0.35:
        spec["volumes"] = [_volume(rng, i) for i in range(rng.randint(1, 3))]
    sc = _sec_ctx(rng, pod_level=True)
    if sc:
        spec["securityContext"] = sc
    return spec


def _resource(rng, i):
    kind = rng.choice(["Pod"] * 4 + ["Deployment", "CronJob", "Service"])
    meta = {"name": f"r{i}", "namespace": rng.choice(["default", "prod", "kube-system"])}
    if rng.random() < 0.2:
        meta["annotations"] = {
            "container.apparmor.security.beta.kubernetes.io/c0": rng.choice(
                ["runtime/default", "localhost/prof", "unconfined"])
        }
    if kind == "Pod":
        return {"apiVersion": "v1", "kind": "Pod", "metadata": meta, "spec": _pod_spec(rng)}
    if kind == "Deployment":
        return {
            "apiVersion": "apps/v1", "kind": "Deployment", "metadata": meta,
            "spec": {"replicas": 1,
                     "template": {"metadata": {"labels": {"app": "x"}},
                                  "spec": _pod_spec(rng)}},
        }
    if kind == "CronJob":
        return {
            "apiVersion": "batch/v1", "kind": "CronJob", "metadata": meta,
            "spec": {"schedule": "* * * * *",
                     "jobTemplate": {"spec": {"template": {"spec": _pod_spec(rng)}}}},
        }
    return {"apiVersion": "v1", "kind": "Service", "metadata": meta,
            "spec": {"ports": [{"port": 80}]}}


@pytest.mark.parametrize("seed", [7, 21, 1234])
def test_pss_full_set_parity(seed):
    rng = random.Random(seed)
    policies = [expand_policy(p) for p in load_pss_policies()]
    resources = [_resource(rng, i) for i in range(40)]
    operations = [rng.choice(["", "CREATE", "UPDATE", "DELETE"]) for _ in resources]
    check_parity(policies, resources, operations=operations)
