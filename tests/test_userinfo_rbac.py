"""RBAC role resolution (pkg/userinfo/roleRef.go GetRoleRef): bindings
-> resolved roles/clusterRoles during RequestInfo construction, and a
match.clusterRoles policy enforced through the admission HTTP server."""

import http.client
import json

import pytest

from kyverno_tpu.api.policy import ClusterPolicy
from kyverno_tpu.cluster import ClusterSnapshot, PolicyCache, ReportAggregator
from kyverno_tpu.engine.userinfo import get_role_ref, resolve_roles_from_snapshot
from kyverno_tpu.webhooks import AdmissionServer, build_handlers


def rb(name, ns, subjects, ref_kind, ref_name):
    return {"apiVersion": "rbac.authorization.k8s.io/v1", "kind": "RoleBinding",
            "metadata": {"name": name, "namespace": ns},
            "subjects": subjects, "roleRef": {"kind": ref_kind, "name": ref_name}}


def crb(name, subjects, ref_name):
    return {"apiVersion": "rbac.authorization.k8s.io/v1",
            "kind": "ClusterRoleBinding", "metadata": {"name": name},
            "subjects": subjects, "roleRef": {"kind": "ClusterRole", "name": ref_name}}


def test_get_role_ref_user_group_serviceaccount():
    rbs = [
        rb("r1", "ns1", [{"kind": "User", "name": "alice"}], "Role", "editor"),
        rb("r2", "ns2", [{"kind": "Group", "name": "devs"}], "ClusterRole", "viewer"),
        rb("r3", "ns3", [{"kind": "ServiceAccount", "name": "sa1"}], "Role", "runner"),
        rb("r4", "ns4", [{"kind": "ServiceAccount", "name": "sa1",
                          "namespace": "other"}], "Role", "other-role"),
        rb("r5", "ns5", [{"kind": "User", "name": "bob"}], "Role", "bobs"),
    ]
    crbs = [
        crb("c1", [{"kind": "Group", "name": "devs"}], "cluster-admin"),
        crb("c2", [{"kind": "User", "name": "carol"}], "carols"),
        # RoleBinding-kind roleRef inside a CRB is ignored (roleRef.go:69)
        {"kind": "ClusterRoleBinding", "metadata": {"name": "c3"},
         "subjects": [{"kind": "User", "name": "alice"}],
         "roleRef": {"kind": "Role", "name": "nope"}},
    ]
    roles, cluster_roles = get_role_ref(
        rbs, crbs, "alice", ["devs", "system:authenticated"])
    assert roles == ["ns1:editor"]
    assert cluster_roles == ["cluster-admin", "viewer"]

    # service account identity: system:serviceaccount:<ns>:<name>, with
    # the subject namespace defaulting to the binding's namespace
    roles, cluster_roles = get_role_ref(
        rbs, crbs, "system:serviceaccount:ns3:sa1", [])
    assert roles == ["ns3:runner"]
    roles, _ = get_role_ref(rbs, crbs, "system:serviceaccount:other:sa1", [])
    assert roles == ["ns4:other-role"]


def test_resolution_deduplicates_and_sorts():
    rbs = [rb(f"r{i}", "ns", [{"kind": "User", "name": "u"}], "Role", "same")
           for i in range(3)]
    roles, _ = get_role_ref(rbs, [], "u", [])
    assert roles == ["ns:same"]


def test_resolve_from_snapshot():
    snap = ClusterSnapshot()
    snap.upsert(rb("r1", "team-a", [{"kind": "User", "name": "dev1"}], "Role", "dev"))
    snap.upsert(crb("c1", [{"kind": "Group", "name": "ops"}], "ops-admin"))
    snap.upsert({"apiVersion": "v1", "kind": "Pod",
                 "metadata": {"name": "noise", "namespace": "x"}})
    roles, cluster_roles = resolve_roles_from_snapshot(snap, "dev1", ["ops"])
    assert roles == ["team-a:dev"] and cluster_roles == ["ops-admin"]


# -- end to end: match.clusterRoles policy through the admission server

ADMIN_ONLY_POLICY = {
    "apiVersion": "kyverno.io/v1", "kind": "ClusterPolicy",
    "metadata": {"name": "admins-only-privileged"},
    "spec": {
        "validationFailureAction": "Enforce",
        "rules": [{
            "name": "non-admin-privileged",
            "match": {"any": [{"resources": {"kinds": ["Pod"]}}]},
            "exclude": {"any": [{"clusterRoles": ["cluster-admin"]}]},
            "validate": {
                "message": "only cluster-admins may create privileged pods",
                "pattern": {"spec": {"containers": [
                    {"=(securityContext)": {"=(privileged)": "false"}}]}},
            },
        }],
    },
}


@pytest.fixture(scope="module")
def rbac_server():
    cache = PolicyCache()
    cache.set(ClusterPolicy.from_dict(ADMIN_ONLY_POLICY))
    snap = ClusterSnapshot()
    snap.upsert(crb("admins", [{"kind": "User", "name": "root-user"},
                               {"kind": "Group", "name": "admins"}],
                    "cluster-admin"))
    handlers = build_handlers(cache, snap, ReportAggregator(), max_wait_ms=5.0)
    srv = AdmissionServer(handlers, port=0)
    srv.start()
    yield srv
    srv.stop()


def _post(srv, path, body):
    conn = http.client.HTTPConnection("127.0.0.1", srv.port, timeout=60)
    conn.request("POST", path, json.dumps(body),
                 {"Content-Type": "application/json"})
    resp = conn.getresponse()
    data = json.loads(resp.read())
    conn.close()
    return data


def _review(username, groups, uid):
    return {"apiVersion": "admission.k8s.io/v1", "kind": "AdmissionReview",
            "request": {
                "uid": uid, "operation": "CREATE", "namespace": "default",
                "object": {"apiVersion": "v1", "kind": "Pod",
                           "metadata": {"name": "p", "namespace": "default"},
                           "spec": {"containers": [
                               {"name": "c", "image": "nginx",
                                "securityContext": {"privileged": True}}]}},
                "userInfo": {"username": username, "groups": groups},
            }}


def test_cluster_role_gates_admission(rbac_server):
    # plain user: privileged pod blocked
    out = _post(rbac_server, "/validate", _review("alice", ["devs"], "u1"))
    assert out["response"]["allowed"] is False
    # cluster-admin (via user subject): rule excluded, request allowed
    out = _post(rbac_server, "/validate", _review("root-user", [], "u2"))
    assert out["response"]["allowed"] is True
    # cluster-admin (via group subject)
    out = _post(rbac_server, "/validate", _review("eve", ["admins"], "u3"))
    assert out["response"]["allowed"] is True
