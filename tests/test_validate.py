"""MatchPattern tree-walk golden tests.

Corpora extracted from the reference's
pkg/engine/validate/validate_test.go into tests/golden/:

- match_pattern_cases.json — 46 MatchPattern cases with expected
  pass/skip/fail status (conditional + global anchor semantics).
- validate_cases.json — validateMap-level fixtures; cases flagged
  ``substitute`` require $(path) reference pre-substitution and are
  enabled once the variables module provides it.
"""

import json
import os

import pytest

from kyverno_tpu.engine.validate import match_pattern

GOLDEN = os.path.join(os.path.dirname(__file__), "golden")


def load(name):
    with open(os.path.join(GOLDEN, name)) as f:
        return json.load(f)


MATCH_CASES = load("match_pattern_cases.json")
VALIDATE_CASES = load("validate_cases.json")


@pytest.mark.parametrize("case", MATCH_CASES, ids=[c["name"] for c in MATCH_CASES])
def test_match_pattern_status(case):
    err = match_pattern(case["resource"], case["pattern"])
    if case["status"] == "pass":
        assert err is None, f"expected pass, got {err!r}"
    elif case["status"] == "skip":
        assert err is not None and err.skip, f"expected skip, got {err!r}"
    else:
        # "fail" cases: the reference's testMatchPattern helper has no
        # assertion branch for RuleStatusFail (validate_test.go:1665-1688),
        # and several of them (e.g. test-23) actually yield a skip-
        # classified global-anchor error in the Go engine. Assert only
        # "did not pass", mirroring what the reference guarantees.
        assert err is not None, f"expected non-pass, got {err!r}"


@pytest.mark.parametrize("case", VALIDATE_CASES, ids=[c["name"] for c in VALIDATE_CASES])
def test_validate_map_fixtures(case):
    pattern = case["pattern"]
    if case["substitute"]:
        pytest.importorskip("kyverno_tpu.engine.variables")
        from kyverno_tpu.engine.variables import substitute_all

        pattern = substitute_all(None, pattern)
    err = match_pattern(case["resource"], pattern)
    if case["expect"] == "ok":
        assert err is None, f"expected ok, got {err!r}"
    else:
        assert err is not None, "expected failure, got ok"


def test_anchor_parse():
    from kyverno_tpu.engine import anchor

    a = anchor.parse("(image)")
    assert a is not None and a.modifier == anchor.CONDITION and a.key == "image"
    a = anchor.parse("<(image)")
    assert anchor.is_global(a)
    a = anchor.parse("X(host)")
    assert anchor.is_negation(a)
    a = anchor.parse("+(labels)")
    assert anchor.is_add_if_not_present(a)
    a = anchor.parse("=(sc)")
    assert anchor.is_equality(a)
    a = anchor.parse("^(containers)")
    assert anchor.is_existence(a)
    assert anchor.parse("plain") is None
    assert anchor.parse("()") is None  # empty key is not an anchor


def test_negation_anchor():
    # X(key) fails when the key is present
    pattern = {"spec": {"X(hostNetwork)": "true"}}
    assert match_pattern({"spec": {}}, pattern) is None
    err = match_pattern({"spec": {"hostNetwork": "true"}}, pattern)
    assert err is not None and not err.skip


def test_existence_anchor():
    # ^(containers): at least one element must match
    pattern = {"spec": {"^(containers)": [{"name": "busybox"}]}}
    ok = {"spec": {"containers": [{"name": "nginx"}, {"name": "busybox"}]}}
    bad = {"spec": {"containers": [{"name": "nginx"}]}}
    assert match_pattern(ok, pattern) is None
    err = match_pattern(bad, pattern)
    assert err is not None and not err.skip


def test_pss_exclusion_values_without_restricted_field():
    """evaluate.go:104-113: exclusion `values` apply even when no
    restrictedField is declared — uncovered offending values are NOT
    exempted."""
    from kyverno_tpu.pss import _excluded, evaluate_pss

    pod = {"apiVersion": "v1", "kind": "Pod", "metadata": {"name": "p"},
           "spec": {"containers": [{"name": "c", "image": "nginx",
                                    "securityContext": {"capabilities": {
                                        "add": ["SYS_ADMIN"]}}}]}}
    [violation] = evaluate_pss("baseline", pod)
    covered = [{"controlName": "Capabilities", "images": ["nginx"],
                "values": ["SYS_ADMIN"]}]
    uncovered = [{"controlName": "Capabilities", "images": ["nginx"],
                  "values": ["NET_ADMIN"]}]
    blanket = [{"controlName": "Capabilities", "images": ["nginx"]}]
    assert _excluded(violation, pod, covered) is True
    assert _excluded(violation, pod, uncovered) is False
    assert _excluded(violation, pod, blanket) is True
