"""validate.cel rules + ValidatingAdmissionPolicy evaluation
(validate_cel.go:34, validatingadmissionpolicy/validate.go:66)."""

from kyverno_tpu.api.policy import ClusterPolicy
from kyverno_tpu.engine.context import Context
from kyverno_tpu.engine.engine import Engine
from kyverno_tpu.engine.match import RequestInfo
from kyverno_tpu.engine.policycontext import PolicyContext
from kyverno_tpu.vap import CelValidator, validate_vap
from kyverno_tpu.vap.policy import kind_to_resource


def deployment(replicas, labels=None):
    return {
        "apiVersion": "apps/v1", "kind": "Deployment",
        "metadata": {"name": "d", "namespace": "default",
                     "labels": labels or {}},
        "spec": {"replicas": replicas},
    }


def cel_policy(expressions, variables=None, preconditions=None,
               audit_annotations=None, message=""):
    rule = {
        "name": "cel-rule",
        "match": {"any": [{"resources": {"kinds": ["Deployment"]}}]},
        "validate": {"message": message,
                     "cel": {"expressions": expressions}},
    }
    if variables:
        rule["validate"]["cel"]["variables"] = variables
    if audit_annotations:
        rule["validate"]["cel"]["auditAnnotations"] = audit_annotations
    if preconditions:
        rule["celPreconditions"] = preconditions
    return ClusterPolicy.from_dict({
        "apiVersion": "kyverno.io/v1", "kind": "ClusterPolicy",
        "metadata": {"name": "cel-pol"},
        "spec": {"rules": [rule]},
    })


def run(policy, resource, operation="CREATE", old=None):
    ctx = Context()
    ctx.add_resource(resource)
    pctx = PolicyContext(policy=policy, new_resource=resource,
                         old_resource=old or {}, operation=operation,
                         admission_info=RequestInfo(username="alice"),
                         json_context=ctx)
    return Engine().validate(pctx)


def test_cel_rule_pass_and_fail():
    pol = cel_policy([{
        "expression": "object.spec.replicas <= 5",
        "message": "replicas must be <= 5",
    }])
    resp = run(pol, deployment(3))
    [rr] = resp.policy_response.rules
    assert rr.status == "pass"
    resp = run(pol, deployment(9))
    [rr] = resp.policy_response.rules
    assert rr.status == "fail" and rr.message == "replicas must be <= 5"


def test_cel_message_expression_and_variables():
    pol = cel_policy(
        [{"expression": "variables.r <= 5",
          "messageExpression": "'got ' + string(variables.r) + ' replicas'"}],
        variables=[{"name": "r", "expression": "object.spec.replicas"}])
    resp = run(pol, deployment(7))
    [rr] = resp.policy_response.rules
    assert rr.status == "fail" and rr.message == "got 7 replicas"


def test_cel_preconditions_gate():
    pol = cel_policy(
        [{"expression": "false", "message": "always fails"}],
        preconditions=[{"name": "only-update",
                        "expression": "request.operation == 'UPDATE'"}])
    [rr] = run(pol, deployment(1), operation="CREATE").policy_response.rules
    assert rr.status == "skip"
    [rr] = run(pol, deployment(1), operation="UPDATE").policy_response.rules
    assert rr.status == "fail"


def test_cel_error_surfaces_as_error():
    pol = cel_policy([{"expression": "object.spec.missing > 1"}])
    [rr] = run(pol, deployment(1)).policy_response.rules
    assert rr.status == "error" and "no_such_field" in rr.message


def test_cel_old_object():
    pol = cel_policy([{
        "expression": "oldObject == null || object.spec.replicas >= oldObject.spec.replicas",
        "message": "no scale down"}])
    [rr] = run(pol, deployment(2), operation="UPDATE",
               old=deployment(5)).policy_response.rules
    assert rr.status == "fail"
    [rr] = run(pol, deployment(8), operation="UPDATE",
               old=deployment(5)).policy_response.rules
    assert rr.status == "pass"


# -- VAP objects


VAP = {
    "apiVersion": "admissionregistration.k8s.io/v1",
    "kind": "ValidatingAdmissionPolicy",
    "metadata": {"name": "replica-limit"},
    "spec": {
        "matchConstraints": {"resourceRules": [{
            "apiGroups": ["apps"], "apiVersions": ["v1"],
            "operations": ["CREATE", "UPDATE"],
            "resources": ["deployments"]}]},
        "validations": [{
            "expression": "object.spec.replicas <= 5",
            "message": "too many replicas",
            "reason": "Invalid"}],
    },
}


def test_vap_match_and_validate():
    results = validate_vap(VAP, deployment(3))
    assert [r.status for r in results] == ["pass"]
    results = validate_vap(VAP, deployment(10))
    assert results[0].status == "fail"
    assert results[0].message == "too many replicas"
    # non-matching kind -> None
    assert validate_vap(VAP, {"apiVersion": "v1", "kind": "Pod",
                              "metadata": {"name": "p"}}) is None
    # non-matching operation -> None
    assert validate_vap(VAP, deployment(3), operation="DELETE") is None


def test_vap_selectors_and_exclude():
    vap = {**VAP, "spec": {**VAP["spec"],
           "matchConstraints": {
               "resourceRules": [{"apiGroups": ["apps"], "apiVersions": ["v1"],
                                  "operations": ["*"], "resources": ["*"]}],
               "objectSelector": {"matchLabels": {"validate": "yes"}}}}}
    assert validate_vap(vap, deployment(10)) is None
    results = validate_vap(vap, deployment(10, labels={"validate": "yes"}))
    assert results[0].status == "fail"


def test_vap_audit_annotations_and_match_conditions():
    vap = {
        "apiVersion": "admissionregistration.k8s.io/v1",
        "kind": "ValidatingAdmissionPolicy",
        "metadata": {"name": "with-extras"},
        "spec": {
            "matchConditions": [{
                "name": "not-kube-system",
                "expression": "request.namespace != 'kube-system'"}],
            "variables": [{"name": "r", "expression": "object.spec.replicas"}],
            "validations": [{"expression": "variables.r <= 5"}],
            "auditAnnotations": [{
                "key": "replicas-seen",
                "valueExpression": "string(variables.r)"}],
        },
    }
    results = validate_vap(vap, deployment(9))
    assert results[0].status == "fail"
    assert results[0].audit_annotations == {"replicas-seen": "9"}
    # match condition excludes kube-system
    d = deployment(9)
    d["metadata"]["namespace"] = "kube-system"
    results = validate_vap(vap, d)
    assert [r.status for r in results] == ["skip"]


def test_kind_to_resource():
    assert kind_to_resource("Pod") == "pods"
    assert kind_to_resource("NetworkPolicy") == "networkpolicies"
    assert kind_to_resource("Ingress") == "ingresses"
    assert kind_to_resource("MyCustom") == "mycustoms"


def test_validator_compile_error_reported_once():
    v = CelValidator([{"expression": "1 +"}])
    [r] = v.validate(object={})
    assert r.status == "error"


def test_cli_apply_evaluates_vap(tmp_path, capsys):
    """VAP docs loaded among policies are evaluated in-process
    (commands/apply/command.go:213)."""
    import yaml

    from kyverno_tpu.cli.apply import run as apply_run
    import argparse

    pol = tmp_path / "vap.yaml"
    pol.write_text(yaml.safe_dump(VAP))
    res = tmp_path / "dep.yaml"
    res.write_text(yaml.safe_dump(deployment(10)))
    args = argparse.Namespace(
        policies=[str(pol)], resource=[str(res)], engine="scalar",
        audit_warn=False, detailed_results=False, output_json=True,
        registry_fixture=None)
    rc = apply_run(args)
    out = capsys.readouterr().out
    assert rc == 1
    import json as _json
    summary = _json.loads(out.strip().splitlines()[-1])
    assert summary["summary"]["fail"] == 1
    assert summary["failures"][0]["policy"] == "replica-limit"
    assert summary["failures"][0]["message"] == "too many replicas"


def test_kind_to_resource_vowel_y():
    assert kind_to_resource("Gateway") == "gateways"
    assert kind_to_resource("Policy") == "policies"
