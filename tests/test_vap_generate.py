"""Kyverno -> ValidatingAdmissionPolicy generation
(pkg/controllers/validatingadmissionpolicy-generate/controller.go,
pkg/validatingadmissionpolicy/{builder,kyvernopolicy_checker}.go).

The round-trip property is the real check: the generated VAP evaluated
by vap.validate_vap must agree with the scalar engine's verdict for
the source Kyverno CEL rule over a resource corpus."""

import pytest

from kyverno_tpu.api.policy import ClusterPolicy
from kyverno_tpu.vap import (
    VapGenerateController,
    build_vap,
    build_vap_binding,
    can_generate_vap,
    validate_vap,
)


def make_policy(name="check-labels", action="Enforce", rules=None, spec_extra=None):
    spec = {
        "validationFailureAction": action,
        "rules": rules if rules is not None else [{
            "name": "require-team",
            "match": {"any": [{"resources": {
                "kinds": ["Pod", "Deployment"],
                "operations": ["CREATE", "UPDATE"]}}]},
            "validate": {
                "cel": {
                    "expressions": [{
                        "expression": "has(object.metadata.labels) && 'team' in object.metadata.labels",
                        "message": "label 'team' is required",
                    }],
                },
            },
        }],
    }
    spec.update(spec_extra or {})
    return ClusterPolicy.from_dict({
        "apiVersion": "kyverno.io/v1", "kind": "ClusterPolicy",
        "metadata": {"name": name, "uid": "u-1"}, "spec": spec})


# -- eligibility (kyvernopolicy_checker.go CanGenerateVAP)


def test_eligible_cel_policy():
    ok, msg = can_generate_vap(make_policy())
    assert ok, msg


def test_multiple_rules_ineligible():
    p = make_policy(rules=[
        {"name": "a", "match": {"any": [{"resources": {"kinds": ["Pod"]}}]},
         "validate": {"cel": {"expressions": [{"expression": "true"}]}}},
        {"name": "b", "match": {"any": [{"resources": {"kinds": ["Pod"]}}]},
         "validate": {"cel": {"expressions": [{"expression": "true"}]}}},
    ])
    ok, msg = can_generate_vap(p)
    assert not ok and "multiple rules" in msg


def test_non_cel_rule_ineligible():
    p = make_policy(rules=[{
        "name": "pat", "match": {"any": [{"resources": {"kinds": ["Pod"]}}]},
        "validate": {"pattern": {"metadata": {"name": "?*"}}}}])
    ok, msg = can_generate_vap(p)
    assert not ok and "non CEL" in msg


def test_exclude_and_userinfo_and_namespaces_ineligible():
    base = {"name": "r", "validate": {"cel": {"expressions": [{"expression": "true"}]}}}
    cases = [
        {**base, "match": {"any": [{"resources": {"kinds": ["Pod"]}}]},
         "exclude": {"any": [{"resources": {"namespaces": ["kube-system"]}}]}},
        {**base, "match": {"any": [{"resources": {"kinds": ["Pod"]},
                                    "clusterRoles": ["admin"]}]}},
        {**base, "match": {"any": [{"resources": {"kinds": ["Pod"],
                                                  "namespaces": ["prod"]}}]}},
    ]
    for rule in cases:
        ok, _ = can_generate_vap(make_policy(rules=[rule]))
        assert not ok, rule


def test_multiple_selectors_across_any_ineligible():
    rule = {
        "name": "r",
        "match": {"any": [
            {"resources": {"kinds": ["Pod"],
                           "selector": {"matchLabels": {"a": "b"}}}},
            {"resources": {"kinds": ["Deployment"],
                           "selector": {"matchLabels": {"c": "d"}}}},
        ]},
        "validate": {"cel": {"expressions": [{"expression": "true"}]}}}
    ok, msg = can_generate_vap(make_policy(rules=[rule]))
    assert not ok and "ObjectSelector" in msg


# -- builder (builder.go)


def test_build_vap_shape():
    p = make_policy()
    vap = build_vap(p)
    assert vap["metadata"]["name"] == "check-labels"
    assert vap["metadata"]["labels"]["app.kubernetes.io/managed-by"] == "kyverno"
    assert vap["metadata"]["ownerReferences"][0]["name"] == "check-labels"
    rules = vap["spec"]["matchConstraints"]["resourceRules"]
    # Pod (core/v1) and Deployment (apps/v1) do not share group+version
    assert {"pods"} in [set(r["resources"]) for r in rules]
    assert {"deployments"} in [set(r["resources"]) for r in rules]
    for r in rules:
        assert r["operations"] == ["CREATE", "UPDATE"]
    assert vap["spec"]["validations"][0]["message"] == "label 'team' is required"


def test_build_vap_merges_same_group_version():
    p = make_policy(rules=[{
        "name": "r",
        "match": {"any": [{"resources": {"kinds": ["Deployment", "StatefulSet"]}}]},
        "validate": {"cel": {"expressions": [{"expression": "true"}]}}}])
    rules = build_vap(p)["spec"]["matchConstraints"]["resourceRules"]
    assert len(rules) == 1
    assert set(rules[0]["resources"]) == {"deployments", "statefulsets"}
    assert rules[0]["apiGroups"] == ["apps"]
    # no operations declared -> default CREATE+UPDATE (builder.go:189)
    assert rules[0]["operations"] == ["CREATE", "UPDATE"]


def test_build_binding_actions():
    b = build_vap_binding(make_policy(action="Enforce"))
    assert b["spec"]["validationActions"] == ["Deny"]
    assert b["metadata"]["name"] == "check-labels-binding"
    assert b["spec"]["policyName"] == "check-labels"
    b = build_vap_binding(make_policy(action="Audit"))
    assert b["spec"]["validationActions"] == ["Audit", "Warn"]


# -- round-trip: generated VAP verdicts == scalar engine verdicts


def corpus():
    out = []
    for i in range(12):
        labels = {}
        if i % 3 == 0:
            labels["team"] = f"t{i}"
        if i % 4 == 0:
            labels["app"] = "x"
        kind = ["Pod", "Deployment", "Service"][i % 3]
        out.append({
            "apiVersion": "apps/v1" if kind == "Deployment" else "v1",
            "kind": kind,
            "metadata": {"name": f"r{i}", "namespace": "default",
                         **({"labels": labels} if labels else {})},
            "spec": {},
        })
    return out


def scalar_verdict(policy, resource):
    """pass/fail/None(not matched) from the scalar engine."""
    from kyverno_tpu.engine.engine import Engine
    from kyverno_tpu.tpu.engine import build_scan_context

    eng = Engine()
    resp = eng.validate(build_scan_context(policy, resource, {}, "CREATE"))
    for rr in resp.policy_response.rules:
        return rr.status
    return None


def vap_verdict(vap, resource):
    results = validate_vap(vap, resource, operation="CREATE")
    if results is None:
        return None
    statuses = {r.status for r in results}
    if "fail" in statuses:
        return "fail"
    if "error" in statuses:
        return "error"
    if statuses == {"skip"}:
        return "skip"  # matchConditions excluded the resource
    return "pass"


def test_round_trip_parity():
    policy = make_policy()
    vap = build_vap(policy)
    checked = 0
    for res in corpus():
        sv = scalar_verdict(policy, res)
        vv = vap_verdict(vap, res)
        # both engines must agree on matched resources' verdicts; the
        # kyverno engine reports NOT MATCHED (None) where the VAP's
        # matchConstraints exclude the resource
        assert (sv is None) == (vv is None), (res["metadata"]["name"], sv, vv)
        if sv is not None:
            assert sv == vv, (res["metadata"]["name"], sv, vv)
            checked += 1
    assert checked >= 6  # corpus actually exercised both verdict kinds


def test_round_trip_with_match_conditions():
    rule = {
        "name": "r",
        "match": {"any": [{"resources": {"kinds": ["Pod"]}}]},
        "celPreconditions": [{"name": "named",
                              "expression": "object.metadata.name != 'skipme'"}],
        "validate": {"cel": {"expressions": [
            {"expression": "!has(object.spec.hostNetwork) || !object.spec.hostNetwork",
             "message": "no hostNetwork"}]}}}
    policy = make_policy(rules=[rule])
    vap = build_vap(policy)
    assert vap["spec"]["matchConditions"] == rule["celPreconditions"]
    pods = [
        {"apiVersion": "v1", "kind": "Pod",
         "metadata": {"name": "skipme"}, "spec": {"hostNetwork": True}},
        {"apiVersion": "v1", "kind": "Pod",
         "metadata": {"name": "bad"}, "spec": {"hostNetwork": True}},
        {"apiVersion": "v1", "kind": "Pod",
         "metadata": {"name": "ok"}, "spec": {}},
    ]
    for pod in pods:
        sv = scalar_verdict(policy, pod)
        vv = vap_verdict(vap, pod)
        norm = {None: None, "skip": None}.get(vv, vv)
        snorm = {None: None, "skip": None}.get(sv, sv)
        assert snorm == norm, (pod["metadata"]["name"], sv, vv)


# -- controller reconcile (controller.go:287)


class SinkSnapshot:
    def __init__(self):
        self.objs = {}

    def upsert(self, resource):
        self.objs[(resource["kind"], resource["metadata"]["name"])] = resource

    def delete(self, resource):
        self.objs.pop((resource["kind"], resource["metadata"]["name"]), None)


def test_controller_reconcile_upsert_and_delete():
    sink = SinkSnapshot()
    ctrl = VapGenerateController(sink)
    p = make_policy()
    ctrl.reconcile(p)
    assert ("ValidatingAdmissionPolicy", "check-labels") in sink.objs
    assert ("ValidatingAdmissionPolicyBinding", "check-labels-binding") in sink.objs
    assert ctrl.status["check-labels"] == (True, "")
    # policy becomes ineligible -> pair deleted, reason recorded
    p2 = make_policy(rules=[{
        "name": "pat", "match": {"any": [{"resources": {"kinds": ["Pod"]}}]},
        "validate": {"pattern": {"metadata": {"name": "?*"}}}}])
    ctrl.reconcile(p2)
    assert ("ValidatingAdmissionPolicy", "check-labels") not in sink.objs
    assert not ctrl.status["check-labels"][0]
    ctrl.reconcile(p)
    ctrl.on_policy_deleted("check-labels")
    assert not sink.objs


def test_controller_exception_suppresses_generation():
    sink = SinkSnapshot()
    exc = {"apiVersion": "kyverno.io/v2", "kind": "PolicyException",
           "metadata": {"name": "e"},
           "spec": {"exceptions": [{"policyName": "check-labels",
                                    "ruleNames": ["require-team"]}],
                    "match": {"any": [{"resources": {"kinds": ["Pod"]}}]}}}
    ctrl = VapGenerateController(sink, exceptions=[exc])
    ctrl.reconcile(make_policy())
    assert not sink.objs
    assert "exception" in ctrl.status["check-labels"][1]


def test_build_vap_does_not_merge_divergent_operations():
    """Two any-entries sharing group+version but with different
    operations must stay separate rules (merging would drop the second
    entry's operations — a reference bug deliberately not replicated)."""
    p = make_policy(rules=[{
        "name": "r",
        "match": {"any": [
            {"resources": {"kinds": ["ConfigMap"], "operations": ["CREATE"]}},
            {"resources": {"kinds": ["Secret"], "operations": ["DELETE"]}},
        ]},
        "validate": {"cel": {"expressions": [{"expression": "true"}]}}}])
    rules = build_vap(p)["spec"]["matchConstraints"]["resourceRules"]
    ops = {tuple(r["resources"]): r["operations"] for r in rules}
    assert ops == {("configmaps",): ["CREATE"], ("secrets",): ["DELETE"]}
