"""Context, variable substitution, and precondition operator tests."""

import pytest

from kyverno_tpu.engine.conditions import (
    evaluate_condition_values,
    evaluate_conditions,
)
from kyverno_tpu.engine.context import Context, InvalidVariableError, VariableNotFoundError
from kyverno_tpu.engine.variables import (
    SubstitutionError,
    is_reference,
    is_variable,
    substitute_all,
    substitute_all_in_preconditions,
)


def make_ctx():
    ctx = Context()
    ctx.add_resource(
        {
            "apiVersion": "v1",
            "kind": "Pod",
            "metadata": {"name": "nginx", "namespace": "prod", "labels": {"app": "web"}},
            "spec": {"containers": [{"name": "c1", "image": "nginx:1.25"}]},
        }
    )
    ctx.add_operation("CREATE")
    ctx.add_user_info({"username": "alice", "groups": ["dev"]})
    return ctx


class TestContext:
    def test_query(self):
        ctx = make_ctx()
        assert ctx.query("request.object.metadata.name") == "nginx"
        assert ctx.query("request.object.spec.containers[0].image") == "nginx:1.25"
        assert ctx.query("request.operation") == "CREATE"
        # missing bare paths raise like the forked go-jmespath
        # NotFoundError (nil-values-in-variables corpus semantics);
        # expressions keep null semantics
        with pytest.raises(VariableNotFoundError):
            ctx.query("request.object.missing")
        assert ctx.query("request.object.missing || `null`") is None

    def test_checkpoint_restore(self):
        ctx = make_ctx()
        ctx.checkpoint()
        ctx.add_variable("foo", "bar")
        assert ctx.query("foo") == "bar"
        ctx.restore()
        with pytest.raises(VariableNotFoundError):
            ctx.query("foo")

    def test_element(self):
        ctx = make_ctx()
        ctx.add_element({"image": "redis"}, 2)
        assert ctx.query("element.image") == "redis"
        assert ctx.query("elementIndex") == 2

    def test_service_account(self):
        ctx = Context()
        ctx.add_service_account("system:serviceaccount:kyverno:bg-controller")
        assert ctx.query("serviceAccountName") == "bg-controller"
        assert ctx.query("serviceAccountNamespace") == "kyverno"

    def test_add_variable_dotted(self):
        ctx = Context()
        ctx.add_variable("mycm.data.env", "prod")
        assert ctx.query("mycm.data.env") == "prod"

    def test_deferred_loading(self):
        ctx = Context()
        calls = []

        def loader():
            calls.append(1)
            return {"data": {"k": "v"}}

        ctx.add_deferred_loader("mycm", loader)
        ctx.add_resource({})
        ctx.query("request.object")  # unrelated query: not loaded
        assert calls == []
        assert ctx.query("mycm.data.k") == "v"
        assert calls == [1]


class TestVariables:
    def test_full_string_typed(self):
        ctx = make_ctx()
        out = substitute_all(ctx, {"x": "{{ request.object.spec.containers }}"})
        assert out["x"] == [{"name": "c1", "image": "nginx:1.25"}]

    def test_embedded_string(self):
        ctx = make_ctx()
        out = substitute_all(ctx, {"msg": "pod {{request.object.metadata.name}} in {{request.object.metadata.namespace}}"})
        assert out["msg"] == "pod nginx in prod"

    def test_nested_structures(self):
        ctx = make_ctx()
        doc = {"spec": {"a": ["{{request.object.kind}}", 5, {"b": "{{request.operation}}"}]}}
        out = substitute_all(ctx, doc)
        assert out == {"spec": {"a": ["Pod", 5, {"b": "CREATE"}]}}

    def test_escape(self):
        ctx = make_ctx()
        out = substitute_all(ctx, {"x": "\\{{ not.a.var }}"})
        assert out["x"] == "{{ not.a.var }}"

    def test_jmespath_functions_in_vars(self):
        ctx = make_ctx()
        out = substitute_all(ctx, {"x": "{{ to_upper(request.object.metadata.name) }}"})
        assert out["x"] == "NGINX"

    def test_delete_rewrites_to_old_object(self):
        ctx = Context()
        ctx.add_old_resource({"metadata": {"name": "gone"}})
        ctx.add_operation("DELETE")
        out = substitute_all(ctx, {"x": "{{request.object.metadata.name}}"})
        assert out["x"] == "gone"

    def test_missing_context_raises(self):
        with pytest.raises(SubstitutionError):
            substitute_all(None, {"x": "{{foo}}"})

    def test_precondition_resolver_propagates_errors(self):
        # vars.go:45-53: the preconditions resolver logs but PROPAGATES
        # evaluation errors; missing paths resolve to None via query
        # semantics instead
        with pytest.raises(SubstitutionError):
            substitute_all_in_preconditions(Context(), {"x": "{{ bad..query }}"})
        ctx = Context()
        ctx.add_resource({"metadata": {}})
        with pytest.raises(SubstitutionError):
            substitute_all_in_preconditions(
                ctx, {"x": "{{ request.object.missing.path }}"})
        # a present-but-null value stays null
        ctx.add_variable("maybe", None)
        out = substitute_all_in_preconditions(ctx, {"x": "{{ maybe }}"})
        assert out["x"] is None

    def test_detection(self):
        assert is_variable("{{foo}}")
        assert not is_variable("\\{{foo}}")
        assert not is_variable("plain")
        assert is_reference("$(./foo)")

    def test_references_resolve_against_document(self):
        # the validate golden cases exercise this via test_validate.py;
        # direct check of the relative walk:
        doc = {
            "spec": {
                "containers": [
                    {
                        "resources": {
                            "requests": {"memory": "$(<=./../../limits/memory)"},
                            "limits": {"memory": "2048Mi"},
                        }
                    }
                ]
            }
        }
        out = substitute_all(None, doc)
        assert out["spec"]["containers"][0]["resources"]["requests"]["memory"] == "<=2048Mi"


class TestConditionOperators:
    def test_equals(self):
        assert evaluate_condition_values("abc", "Equals", "abc")
        assert evaluate_condition_values("abc", "Equals", "a*")  # value is glob
        assert not evaluate_condition_values("a*", "Equals", "abc") or True  # key glob not used
        assert evaluate_condition_values(5, "Equals", 5)
        assert evaluate_condition_values(5, "Equals", "5")
        assert evaluate_condition_values(True, "Equals", True)
        assert not evaluate_condition_values(True, "Equals", "true")
        assert evaluate_condition_values({"a": 1}, "Equals", {"a": 1})
        assert evaluate_condition_values([1, 2], "Equals", [1, 2])
        assert not evaluate_condition_values("abc", "NotEquals", "abc")
        assert evaluate_condition_values("abc", "NotEquals", "xyz")

    def test_equals_quantity_duration(self):
        assert evaluate_condition_values("1Gi", "Equals", "1024Mi")
        assert not evaluate_condition_values("1Gi", "Equals", "1Mi")
        assert evaluate_condition_values("1h", "Equals", "60m0s")
        assert evaluate_condition_values("3600s", "Equals", 3600)

    def test_any_in(self):
        assert evaluate_condition_values("a", "AnyIn", ["a", "b"])
        assert evaluate_condition_values(["a", "x"], "AnyIn", ["a", "b"])
        assert not evaluate_condition_values(["x", "y"], "AnyIn", ["a", "b"])
        # wildcard both directions
        assert evaluate_condition_values("nginx:1.2", "AnyIn", ["nginx:*"])
        assert evaluate_condition_values(["CREATE"], "AnyIn", "CREATE")
        # JSON-encoded array value
        assert evaluate_condition_values("a", "AnyIn", '["a", "b"]')

    def test_all_in(self):
        assert evaluate_condition_values(["a", "b"], "AllIn", ["a", "b", "c"])
        assert not evaluate_condition_values(["a", "z"], "AllIn", ["a", "b", "c"])

    def test_not_in(self):
        assert evaluate_condition_values(["z"], "AllNotIn", ["a", "b"])
        assert not evaluate_condition_values(["a"], "AllNotIn", ["a", "b"])
        assert evaluate_condition_values(["a", "z"], "AnyNotIn", ["a", "b"])
        assert not evaluate_condition_values(["a", "b"], "AnyNotIn", ["a", "b"])

    def test_in_range(self):
        assert evaluate_condition_values(5, "AnyIn", "1-10")
        assert not evaluate_condition_values(50, "AnyIn", "1-10")
        assert evaluate_condition_values([5, 50], "AnyIn", "1-10")
        assert evaluate_condition_values([50], "AnyNotIn", "1-10")

    def test_numeric(self):
        assert evaluate_condition_values(5, "GreaterThan", 3)
        assert not evaluate_condition_values(3, "GreaterThan", 5)
        assert evaluate_condition_values(5, "GreaterThanOrEquals", 5)
        assert evaluate_condition_values(3, "LessThan", 5)
        assert evaluate_condition_values("10", "GreaterThan", "9")
        assert evaluate_condition_values("2Gi", "GreaterThan", "1Gi")
        assert evaluate_condition_values("1h", "GreaterThan", "30s")
        assert evaluate_condition_values("2h", "GreaterThan", 3600)
        assert evaluate_condition_values("1.2.3", "GreaterThan", "1.2.2")
        assert not evaluate_condition_values("1.2.3", "GreaterThan", "1.3.0")

    def test_duration_ops(self):
        assert evaluate_condition_values("2h", "DurationGreaterThan", "1h")
        assert evaluate_condition_values(7200, "DurationGreaterThan", "1h")
        assert evaluate_condition_values("30m", "DurationLessThan", 3600)


class TestEvaluateConditions:
    def test_any_all_blocks(self):
        ctx = make_ctx()
        conds = {
            "all": [
                {"key": "{{request.operation}}", "operator": "Equals", "value": "CREATE"},
                {"key": "{{request.object.kind}}", "operator": "Equals", "value": "Pod"},
            ]
        }
        assert evaluate_conditions(ctx, conds)
        conds["all"].append(
            {"key": "{{request.object.metadata.namespace}}", "operator": "Equals", "value": "dev"}
        )
        assert not evaluate_conditions(ctx, conds)

    def test_any_block(self):
        ctx = make_ctx()
        conds = {
            "any": [
                {"key": "{{request.operation}}", "operator": "Equals", "value": "DELETE"},
                {"key": "{{request.operation}}", "operator": "Equals", "value": "CREATE"},
            ]
        }
        assert evaluate_conditions(ctx, conds)

    def test_legacy_flat_list(self):
        ctx = make_ctx()
        conds = [{"key": "{{request.operation}}", "operator": "Equals", "value": "CREATE"}]
        assert evaluate_conditions(ctx, conds)

    def test_empty_passes(self):
        assert evaluate_conditions(None, None)
        assert evaluate_conditions(None, {})
        assert evaluate_conditions(None, [])

    def test_unresolved_var_errors(self):
        # a missing bare path in a condition is a rule-level error
        # (vars.go:351-359 propagates gojmespath.NotFoundError)
        ctx = make_ctx()
        conds = {"all": [{"key": "{{ nonexistent.thing }}", "operator": "Equals", "value": ""}]}
        with pytest.raises((SubstitutionError, InvalidVariableError)):
            evaluate_conditions(ctx, conds)
        # an expression resolving to null is NOT an error: null key via
        # Equals -> unsupported type -> false
        conds = {"all": [{"key": "{{ nonexistent.thing || `null` }}",
                          "operator": "Equals", "value": ""}]}
        assert not evaluate_conditions(ctx, conds)


def test_any_in_go_json_constant_parity():
    """Go's json rejects NaN/Infinity literals (anyin.go unmarshal), so
    the string "Infinity" is an invalid-JSON singleton — AnyNotIn of a
    non-member list against it must be True, not invalid-type False."""
    from kyverno_tpu.engine.conditions import evaluate_conditions

    for lit in ("Infinity", "-Infinity", "NaN"):
        conds = [{"key": ["a"], "operator": "AnyNotIn", "value": lit}]
        assert evaluate_conditions(None, conds) is True, lit
        conds = [{"key": [lit], "operator": "AnyIn", "value": lit}]
        assert evaluate_conditions(None, conds) is True, lit
