"""Vocabulary-encoding parity: densify(encode_resources_vocab(...))
must reproduce every lane of the dense encode_resources(...) output.

The vocab form is the transferable representation (row dedup + device
gather, flatten.py "Vocabulary encoding"); the dense form is the
oracle. Any divergence is a wrong-verdict bug, so the comparison is
exact, lane by lane, over adversarial resource shapes.
"""

import numpy as np
import pytest

from kyverno_tpu.tpu.evaluator import batch_to_host, densify
from kyverno_tpu.tpu.flatten import (
    EncodeConfig,
    encode_resources,
    encode_resources_vocab,
)
from kyverno_tpu.tpu.hashing import hash_path
from kyverno_tpu.tpu.metadata import encode_metadata


def _assert_parity(resources, cfg=None, byte_paths=(), key_byte_paths=()):
    cfg = cfg or EncodeConfig()
    dense = encode_resources(resources, cfg, byte_paths, key_byte_paths)
    vocab = encode_resources_vocab(resources, cfg, byte_paths, key_byte_paths)
    meta = encode_metadata(resources)
    want = batch_to_host(dense, meta)
    got = {k: np.asarray(v) for k, v in
           densify(vocab.to_host(meta, v_bucket=None)).items()}
    assert set(got) == set(want)
    for k in sorted(want):
        assert np.array_equal(got[k], np.asarray(want[k])), (
            f"lane {k} diverges:\n{np.asarray(want[k])}\nvs\n{got[k]}")


def _pods(n):
    out = []
    for i in range(n):
        out.append({
            "apiVersion": "v1", "kind": "Pod",
            "metadata": {"name": f"p{i}", "namespace": "ns",
                         "labels": {"app": f"a{i % 3}"}},
            "spec": {
                "hostNetwork": i % 4 == 0,
                "containers": [
                    {"name": f"c{j}", "image": "nginx:1.25",
                     "securityContext": {"privileged": j % 2 == 0},
                     "resources": {"limits": {"memory": "1Gi"}}}
                    for j in range(1 + i % 3)
                ],
            },
        })
    return out


def test_parity_pods():
    _assert_parity(_pods(17))


def test_parity_empty_and_scalars():
    _assert_parity([
        {},
        {"a": None, "b": True, "c": False, "d": 0, "e": -1.5, "f": "s"},
        {"nums": [1, 2.5, "3", "1e3", "0x10", "10Mi", "3h2m", "-0.0"]},
        {"zero": 0.0, "negzero": -0.0, "big": 2**40},
    ])


def test_parity_nested_arrays_scopes():
    _assert_parity([
        {"spec": {"containers": [
            {"env": [{"name": "A", "value": "x"}, {"name": "B"}]},
            {"env": [{"name": "A", "value": "x"}]},
        ]}},
        {"matrix": [[1, 2], [3, [4, 5]]]},
    ])


def test_parity_glob_values_and_wild_keys():
    _assert_parity([
        {"metadata": {"annotations": {"k*y": "v?l", "plain": "x"}}},
        {"v": "has*glob", "w": "q?mark"},
    ])


def test_parity_byte_pool():
    bp = {hash_path(("spec", "image"))}
    kbp = {hash_path(("metadata", "annotations"))}
    res = [
        {"spec": {"image": "nginx:latest"}},
        {"spec": {"image": "nginx:latest"},
         "metadata": {"annotations": {"a": "runtime/default", "b": "localhost/x"}}},
        {"spec": {"image": "other"}, "metadata": {"annotations": {}}},
    ]
    _assert_parity(res, byte_paths=bp, key_byte_paths=kbp)


def test_parity_row_cap_fallback():
    cfg = EncodeConfig(max_rows=8)
    res = [{"a": {f"k{i}": i for i in range(20)}}, {"b": 1}]
    _assert_parity(res, cfg=cfg)
    vb = encode_resources_vocab(res, cfg)
    assert vb.fallback[0] == 1 and vb.fallback[1] == 0


def test_parity_instance_overflow():
    cfg = EncodeConfig(max_instances=2)
    res = [
        {"spec": {"containers": [{"n": i} for i in range(4)]}},   # depth0 overflow
        {"spec": {"containers": [{"env": [{"v": i} for i in range(4)]}]}},  # depth1
    ]
    _assert_parity(res, cfg=cfg)


def test_parity_pool_overflow_marks_fallback():
    cfg = EncodeConfig(byte_pool_slots=1, byte_pool_width=4)
    bp = {hash_path(("a",)), hash_path(("b",))}
    _assert_parity([{"a": "xy", "b": "zw"}, {"a": "toolongvalue"}],
                   cfg=cfg, byte_paths=bp)


def test_vocab_dedup_is_effective():
    res = _pods(64)
    vb = encode_resources_vocab(res)
    n_rows_total = int(vb.n_rows.sum())
    assert vb.vocab_size < n_rows_total / 4, (
        f"vocab {vb.vocab_size} rows vs {n_rows_total} total — dedup ineffective")


def test_native_encoder_parity():
    """The C walk (native/fastencode.c) must agree with the Python
    vocab encoder on densified output, n_rows and fallback for every
    adversarial shape (vocab internals may order rows differently)."""
    from kyverno_tpu.native import load
    from kyverno_tpu.tpu import flatten as F

    native = load()
    if native is None:
        pytest.skip("native toolchain unavailable")

    def py_encode(res, cfg, bp, kbp):
        enc = F._FastEncoder(F._CfgShell(cfg), set(bp), set(kbp))
        vb = F.VocabBatch(len(res), cfg)
        for i, r in enumerate(res):
            enc.begin(i)
            enc.walk(r, F._ROOT_REC, 0, 0, -1, -1, 0)
            vb.n_rows[i] = enc.row
            vb.fallback[i] = 0 if enc.ok else 1
        F._finish_vocab(enc, vb)
        return vb

    cases = [
        (_pods(23), EncodeConfig(), (), ()),
        ([{}, {"a": None, "b": True, "c": 0, "d": -1.5, "e": "s",
               "n": [1, "2", "10Mi", "3h", "0x10", "-0.0", 2**40, 1e20]}],
         EncodeConfig(), (), ()),
        ([{1: "intkey", "m": {2.5: "floatkey"}}], EncodeConfig(), (), ()),
        ([{"metadata": {"annotations": {"k*y": "v?l", "a": "runtime/default"}}},
          {"v": "g*b"}], EncodeConfig(),
         {hash_path(("v",))}, {hash_path(("metadata", "annotations"))}),
        ([{"a": {f"k{i}": i for i in range(20)}}, {"b": 1}],
         EncodeConfig(max_rows=8), (), ()),
        ([{"spec": {"containers": [{"n": i} for i in range(4)]}},
          {"spec": {"containers": [{"env": [{"v": i} for i in range(4)]}]}}],
         EncodeConfig(max_instances=2), (), ()),
        ([{"a": "xy", "b": "zw"}, {"a": "toolongvalue"}],
         EncodeConfig(byte_pool_slots=1, byte_pool_width=4),
         {hash_path(("a",)), hash_path(("b",))}, ()),
        # memo tables grow mid-call: entries must stay pointer-stable
        # (regression for use-after-free on scalar/path table growth)
        ([{"x": f"u{i}"} for i in range(9000)], EncodeConfig(), (), ()),
        ([{"arr": [{f"uniquekey{i}": 1} for i in range(600)]}],
         EncodeConfig(max_rows=2048, max_instances=1024), (), ()),
    ]
    for res, cfg, bp, kbp in cases:
        nat = F._encode_vocab_native(native, list(res), cfg, bp, kbp)
        pyv = py_encode(res, cfg, bp, kbp)
        assert np.array_equal(nat.n_rows, pyv.n_rows)
        assert np.array_equal(nat.fallback, pyv.fallback)
        meta = encode_metadata(res)
        got = {k: np.asarray(v) for k, v in densify(nat.to_host(meta)).items()}
        want = {k: np.asarray(v) for k, v in densify(pyv.to_host(meta)).items()}
        assert set(got) == set(want)
        for k in sorted(want):
            assert np.array_equal(got[k], want[k]), f"lane {k} diverges"


def test_bucket_padding_shapes():
    res = _pods(5)
    vb = encode_resources_vocab(res)
    meta = encode_metadata(res)
    host = vb.to_host(meta, v_bucket=4096, s_bucket=512)
    assert host["vocab_norm_hi"].shape == (4096,)
    assert host["pool_svocab"].shape[0] == 512
    # padded vocab rows are invalid and scope lanes keep the -1 default
    assert host["vocab_valid"][vb.vocab_size:].sum() == 0
    assert (host["vocab_scope1"][vb.vocab_size:] == -1).all()
    with pytest.raises(ValueError):
        vb.to_host(meta, v_bucket=2)
