"""Webhook-config generation from the live policy set + TLS cert
generation/rotation (pkg/controllers/webhook/controller.go,
pkg/tls/renewer.go)."""

import datetime
import http.client
import json
import ssl

import pytest

from kyverno_tpu.api.policy import ClusterPolicy
from kyverno_tpu.cluster import PolicyCache
from kyverno_tpu.cluster.webhookconfig import (
    FINE_GRAINED_ANNOTATION,
    WebhookConfigGenerator,
)
from kyverno_tpu.utils.tlsutil import CertRenewer
from kyverno_tpu.webhooks import AdmissionServer, build_handlers


def policy(name, kinds=("Pod",), failure_policy=None, annotations=None,
           rule_kind="validate"):
    rule = {"name": "r",
            "match": {"any": [{"resources": {"kinds": list(kinds)}}]}}
    if rule_kind == "validate":
        rule["validate"] = {"pattern": {"metadata": {"name": "?*"}}}
    else:
        rule["mutate"] = {"patchStrategicMerge": {"metadata": {
            "labels": {"+(x)": "y"}}}}
    spec = {"rules": [rule]}
    if failure_policy:
        spec["failurePolicy"] = failure_policy
    return ClusterPolicy.from_dict({
        "apiVersion": "kyverno.io/v1", "kind": "ClusterPolicy",
        "metadata": {"name": name, "annotations": annotations or {}},
        "spec": spec,
    })


def test_webhook_config_from_policies_and_failure_policy_split():
    cache = PolicyCache()
    cache.set(policy("fail-pol", kinds=("Pod",)))
    cache.set(policy("ignore-pol", kinds=("apps/v1/Deployment",),
                     failure_policy="Ignore"))
    gen = WebhookConfigGenerator(cache)
    assert gen.reconcile(ca_bundle="CA") is True
    cfg = gen.configs["validating"]
    byname = {w["name"]: w for w in cfg["webhooks"]}
    fail = byname["validate.kyverno.svc-fail"]
    ignore = byname["validate.kyverno.svc-ignore"]
    assert fail["failurePolicy"] == "Fail"
    assert ignore["failurePolicy"] == "Ignore"
    # pods imply pods/ephemeralcontainers (utils.go:81-84); the cache
    # autogen-expands the Pod policy, so the surface also includes the
    # pod controllers (apps/batch groups)
    core = [r for r in fail["rules"] if r["apiGroups"] == [""]][0]
    assert {"pods", "pods/ephemeralcontainers"} <= set(core["resources"])
    apps = [r for r in fail["rules"] if r["apiGroups"] == ["apps"]][0]
    assert "deployments" in apps["resources"]
    [irule] = ignore["rules"]
    assert irule["apiGroups"] == ["apps"] and irule["resources"] == ["deployments"]
    assert fail["clientConfig"]["service"]["path"] == "/validate/fail"
    assert fail["clientConfig"]["caBundle"] == "CA"


def test_webhook_config_reacts_to_policy_change():
    cache = PolicyCache()
    cache.set(policy("p1", kinds=("ConfigMap",)))
    gen = WebhookConfigGenerator(cache)
    gen.reconcile()
    assert gen.serves("ConfigMap") and not gen.serves("apps/v1/Deployment")
    # adding a Deployment policy changes the served surface
    cache.set(policy("p2", kinds=("apps/v1/Deployment",)))
    assert gen.reconcile() is True
    assert gen.serves("apps/v1/Deployment")
    # removing it shrinks the surface again
    cache.unset("p2")
    assert gen.reconcile() is True
    assert not gen.serves("apps/v1/Deployment")
    # no revision change -> no work
    assert gen.reconcile() is False


def test_fine_grained_webhook_per_policy():
    cache = PolicyCache()
    cache.set(policy("special", kinds=("Pod",),
                     annotations={FINE_GRAINED_ANNOTATION: "true"}))
    gen = WebhookConfigGenerator(cache)
    gen.reconcile()
    [wh] = gen.configs["validating"]["webhooks"]
    assert wh["name"] == "validate.kyverno.svc-fail-finegrained-special"
    assert wh["clientConfig"]["service"]["path"] == "/validate/fail/finegrained/special"


def test_mutating_config_covers_mutate_and_verify_images():
    cache = PolicyCache()
    cache.set(policy("mut", kinds=("Pod",), rule_kind="mutate"))
    gen = WebhookConfigGenerator(cache)
    gen.reconcile()
    cfg = gen.configs["mutating"]
    assert cfg["kind"] == "MutatingWebhookConfiguration"
    [wh] = cfg["webhooks"]
    assert wh["clientConfig"]["service"]["path"] == "/mutate/fail"


# ---------------------------------------------------------------------------
# TLS


@pytest.mark.requires_crypto
def test_cert_generation_and_renewal(tmp_path):
    pytest.importorskip("cryptography")
    now = [datetime.datetime.now(datetime.timezone.utc)]
    r = CertRenewer(str(tmp_path), ["localhost"], clock=lambda: now[0],
                    cert_validity_s=100 * 24 * 3600)
    assert r.renew_if_needed() is True
    first = open(r.certfile, "rb").read()
    assert b"BEGIN CERTIFICATE" in first
    # inside validity: no renewal
    assert r.renew_if_needed() is False
    # move clock into renew-before window (15d before expiry)
    now[0] = now[0] + datetime.timedelta(days=90)
    assert r.renew_if_needed() is True
    assert open(r.certfile, "rb").read() != first
    assert r.renewals == 2


@pytest.mark.requires_crypto
def test_cert_rotation_without_dropping_requests(tmp_path):
    """renewer.go:94: rolling the cert must not interrupt serving —
    requests succeed before and after the rotation, and the new
    handshake presents the new certificate."""
    pytest.importorskip("cryptography")
    renewer = CertRenewer(str(tmp_path), ["127.0.0.1", "localhost"])
    renewer.renew_if_needed()
    cache = PolicyCache()
    handlers = build_handlers(cache)
    srv = AdmissionServer(handlers, port=0, certfile=renewer.certfile,
                          keyfile=renewer.keyfile)
    renewer.on_reload = lambda c, k, ca: srv.reload_cert(c, k)
    srv.start()
    try:
        ctx = ssl.create_default_context(cafile=renewer.cafile)
        ctx.check_hostname = False

        def probe():
            conn = http.client.HTTPSConnection("127.0.0.1", srv.port,
                                               context=ctx, timeout=10)
            conn.connect()
            cert = conn.sock.getpeercert(binary_form=True)
            conn.request("GET", "/health/liveness")
            resp = conn.getresponse()
            body = resp.read()
            conn.close()
            return resp.status, body, cert

        status, body, cert1 = probe()
        assert status == 200 and body == b"ok"
        # force a rotation (fresh serving pair under the same CA)
        renewer.cert = None
        assert renewer.renew_if_needed() is True
        status, body, cert2 = probe()
        assert status == 200 and body == b"ok"
        assert cert1 != cert2  # new serving cert actually presented
    finally:
        srv.stop()
        handlers.batcher.stop()


def test_parse_kind_subresource_and_gctx_unsubscribe():
    from kyverno_tpu.cluster.webhookconfig import _parse_kind
    from kyverno_tpu.cluster.snapshot import ClusterSnapshot
    from kyverno_tpu.globalcontext import GlobalContextStore

    assert _parse_kind("Pod/exec") == ("", "v1", ["pods/exec"], "Namespaced")
    assert _parse_kind("apps/v1/Deployment") == \
        ("apps", "v1", ["deployments"], "Namespaced")
    assert _parse_kind("Pod") == ("", "v1", ["pods"], "Namespaced")
    assert _parse_kind("*", policy_scope="Namespaced") == \
        ("*", "*", ["*"], "Namespaced")
    assert _parse_kind("CustomResourceDefinition")[3] == "*"
    # reconciling the same gctx entry twice must not leak subscribers
    snap = ClusterSnapshot()
    store = GlobalContextStore(snapshot=snap)
    doc = {"metadata": {"name": "e"},
           "spec": {"kubernetesResource": {"group": "", "version": "v1",
                                           "resource": "pods"}}}
    before = len(snap._subscribers)
    store.apply(doc)
    store.apply(doc)
    store.apply(doc)
    assert len(snap._subscribers) == before + 1


# -- shutdown hygiene + init janitor (server.go:243, cmd/kyverno-init)


def test_shutdown_deregisters_webhook_configs_and_releases_leases():
    from kyverno_tpu.cluster.leaderelection import LeaseStore
    from kyverno_tpu.cluster.lifecycle import (
        HEALTH_LEASE, cleanup_on_shutdown)
    from kyverno_tpu.cluster.snapshot import ClusterSnapshot
    from kyverno_tpu.cluster.webhookconfig import MANAGED_BY_LABEL

    snap = ClusterSnapshot()
    snap.upsert({"apiVersion": "admissionregistration.k8s.io/v1",
                 "kind": "ValidatingWebhookConfiguration",
                 "metadata": {"name": "kyverno-resource-validating-webhook-cfg",
                              "labels": {MANAGED_BY_LABEL: "kyverno"}}})
    snap.upsert({"apiVersion": "admissionregistration.k8s.io/v1",
                 "kind": "ValidatingWebhookConfiguration",
                 "metadata": {"name": "other-team-webhook"}})
    store = LeaseStore()
    store.try_acquire_or_renew(HEALTH_LEASE, "me", 60)
    deleted = cleanup_on_shutdown(snap, store, "me")
    kinds = [r.get("metadata", {}).get("name") for _, r, _ in snap.items()]
    assert "other-team-webhook" in kinds  # unmanaged configs untouched
    assert len(deleted) == 1
    assert store.holder(HEALTH_LEASE) is None


def test_init_janitor_clears_stale_state_and_is_leader_gated():
    from kyverno_tpu.cluster.leaderelection import LeaseStore
    from kyverno_tpu.cluster.lifecycle import JANITOR_LOCK, InitJanitor
    from kyverno_tpu.cluster.snapshot import ClusterSnapshot
    from kyverno_tpu.cluster.webhookconfig import MANAGED_BY_LABEL

    snap = ClusterSnapshot()
    snap.upsert({"kind": "MutatingWebhookConfiguration",
                 "apiVersion": "admissionregistration.k8s.io/v1",
                 "metadata": {"name": "stale",
                              "labels": {MANAGED_BY_LABEL: "kyverno"}}})
    snap.upsert({"kind": "PolicyReport", "apiVersion": "wgpolicyk8s.io/v1alpha2",
                 "metadata": {"name": "old-report", "namespace": "default"}})
    snap.upsert({"kind": "Pod", "apiVersion": "v1",
                 "metadata": {"name": "keep", "namespace": "default"}})
    store = LeaseStore()
    # another janitor holds the lock: quit without touching anything
    store.try_acquire_or_renew(JANITOR_LOCK, "other", 60)
    assert InitJanitor(snap, store, identity="me").run() is None
    assert len(snap) == 3
    store.release(JANITOR_LOCK, "other")
    deleted = InitJanitor(snap, store, identity="me").run()
    assert len(deleted) == 2
    assert [r["kind"] for _, r, _ in snap.items()] == ["Pod"]
    # lock released afterwards
    assert store.holder(JANITOR_LOCK) is None


def test_control_plane_stop_cleans_up():
    from kyverno_tpu.api.policy import ClusterPolicy
    from kyverno_tpu.cli.serve import ControlPlane
    from kyverno_tpu.cluster.webhookconfig import MANAGED_BY_LABEL

    policy = ClusterPolicy.from_dict({
        "apiVersion": "kyverno.io/v1", "kind": "ClusterPolicy",
        "metadata": {"name": "p"},
        "spec": {"rules": [{
            "name": "r", "match": {"any": [{"resources": {"kinds": ["Pod"]}}]},
            "validate": {"message": "m", "pattern": {"metadata": {"name": "?*"}}},
        }]}})
    cp = ControlPlane([policy])
    managed = [r for _, r, _ in cp.snapshot.items()
               if (r.get("metadata", {}).get("labels") or {}).get(MANAGED_BY_LABEL)]
    assert managed, "reconcile must register webhook configurations"
    cp.start(scan_interval=3600)
    cp.stop()
    managed = [r for _, r, _ in cp.snapshot.items()
               if (r.get("metadata", {}).get("labels") or {}).get(MANAGED_BY_LABEL)]
    assert not managed, "stop must deregister webhook configurations"
