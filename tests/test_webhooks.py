"""Admission server: HTTP AdmissionReview round-trips with
micro-batched validation and mutate patches."""

import base64
import concurrent.futures
import http.client
import json

import pytest

from kyverno_tpu.api.policy import ClusterPolicy
from kyverno_tpu.cluster import ClusterSnapshot, PolicyCache, ReportAggregator
from kyverno_tpu.utils.jsonpatch import diff as jsonpatch_diff
from kyverno_tpu.webhooks import AdmissionServer, build_handlers

VALIDATE_POLICY = {
    "apiVersion": "kyverno.io/v1", "kind": "ClusterPolicy",
    "metadata": {"name": "no-privileged"},
    "spec": {
        "validationFailureAction": "Enforce",
        "rules": [{
            "name": "privileged",
            "match": {"any": [{"resources": {"kinds": ["Pod"]}}]},
            "validate": {
                "message": "privileged is forbidden",
                "pattern": {"spec": {"containers": [
                    {"=(securityContext)": {"=(privileged)": "false"}}]}},
            },
        }],
    },
}

MUTATE_POLICY = {
    "apiVersion": "kyverno.io/v1", "kind": "ClusterPolicy",
    "metadata": {"name": "add-label"},
    "spec": {
        "rules": [{
            "name": "add-team-label",
            "match": {"any": [{"resources": {"kinds": ["Pod"]}}]},
            "mutate": {"patchStrategicMerge": {
                "metadata": {"labels": {"+(team)": "core"}}}},
        }],
    },
}


def review(resource, uid="u1", operation="CREATE"):
    return {
        "apiVersion": "admission.k8s.io/v1", "kind": "AdmissionReview",
        "request": {
            "uid": uid, "operation": operation,
            "namespace": (resource.get("metadata") or {}).get("namespace", ""),
            "object": resource,
            "userInfo": {"username": "alice", "groups": ["dev"]},
        },
    }


def pod(name, priv):
    sc = {"securityContext": {"privileged": priv}} if priv is not None else {}
    return {"apiVersion": "v1", "kind": "Pod",
            "metadata": {"name": name, "namespace": "default"},
            "spec": {"containers": [{"name": "c", "image": "nginx", **sc}]}}


@pytest.fixture(scope="module")
def server():
    cache = PolicyCache()
    cache.set(ClusterPolicy.from_dict(VALIDATE_POLICY))
    cache.set(ClusterPolicy.from_dict(MUTATE_POLICY))
    handlers = build_handlers(cache, ClusterSnapshot(), ReportAggregator(),
                              max_wait_ms=5.0)
    srv = AdmissionServer(handlers, port=0)
    srv.start()
    yield srv
    srv.stop()


def _post(srv, path, body):
    conn = http.client.HTTPConnection("127.0.0.1", srv.port, timeout=60)
    conn.request("POST", path, json.dumps(body), {"Content-Type": "application/json"})
    resp = conn.getresponse()
    data = json.loads(resp.read())
    conn.close()
    return data


def test_validate_blocks_enforce_failure(server):
    out = _post(server, "/validate", review(pod("bad", True)))
    assert out["response"]["allowed"] is False
    assert "no-privileged" in out["response"]["status"]["message"]
    out = _post(server, "/validate", review(pod("ok", False)))
    assert out["response"]["allowed"] is True


def test_validate_microbatch_concurrent(server):
    reviews = [review(pod(f"p{i}", i % 2 == 0), uid=f"u{i}") for i in range(16)]
    with concurrent.futures.ThreadPoolExecutor(max_workers=16) as ex:
        outs = list(ex.map(lambda r: _post(server, "/validate", r), reviews))
    for i, out in enumerate(outs):
        assert out["response"]["uid"] == f"u{i}"
        assert out["response"]["allowed"] is (i % 2 != 0)


def test_mutate_returns_json_patch(server):
    out = _post(server, "/mutate", review(pod("m", None)))
    assert out["response"]["allowed"] is True
    patch = json.loads(base64.b64decode(out["response"]["patch"]))
    assert {"op": "add", "path": "/metadata/labels",
            "value": {"team": "core"}} in patch


def test_health_endpoints(server):
    conn = http.client.HTTPConnection("127.0.0.1", server.port, timeout=10)
    conn.request("GET", "/health/liveness")
    assert conn.getresponse().status == 200
    conn.close()


def test_jsonpatch_diff_roundtrip():
    orig = {"a": {"b": 1, "c": [1, 2, 3]}, "d": "x"}
    new = {"a": {"b": 2, "c": [1, 5]}, "e": True}
    ops = jsonpatch_diff(orig, new)
    from kyverno_tpu.engine.mutate import apply_json6902

    assert apply_json6902(orig, ops) == new


def test_scalar_toggle_and_config_filter():
    from kyverno_tpu.config import Configuration, Toggles

    cache = PolicyCache()
    cache.set(ClusterPolicy.from_dict(VALIDATE_POLICY))
    cfg = Configuration()
    cfg.load({"resourceFilters": "[Pod,skip-ns,*]",
              "excludeUsernames": "system:serviceaccount:kyverno:*"})
    handlers = build_handlers(cache, configuration=cfg,
                              toggles=Toggles(engine="scalar"))
    out = handlers.validate(review(pod("bad", True)))
    assert out["response"]["allowed"] is False  # scalar path blocks too
    # resourceFilter short-circuits
    filtered = pod("bad", True)
    filtered["metadata"]["namespace"] = "skip-ns"
    r = review(filtered)
    r["request"]["namespace"] = "skip-ns"
    out = handlers.validate(r)
    assert out["response"]["allowed"] is True
    # excluded service account short-circuits
    r = review(pod("bad2", True))
    r["request"]["userInfo"] = {"username": "system:serviceaccount:kyverno:admission"}
    out = handlers.validate(r)
    assert out["response"]["allowed"] is True
    handlers.batcher.stop()


@pytest.mark.requires_crypto
def test_mutate_runs_image_verification():
    """resource/handlers.go:139-177: the mutate path runs verify-image
    policies after mutate policies; digest patches ride the same
    JSONPatch response, and enforce failures deny."""
    pytest.importorskip("cryptography")
    from kyverno_tpu.images import StaticRegistry

    from kyverno_tpu.images.crypto import generate_keypair

    priv, key = generate_keypair()
    digest = "sha256:" + "cd" * 32
    reg = StaticRegistry()
    reg.add_image("ghcr.io/org/app:v1", digest)
    reg.sign("ghcr.io/org/app:v1", key=priv)
    vi_policy = ClusterPolicy.from_dict({
        "apiVersion": "kyverno.io/v1", "kind": "ClusterPolicy",
        "metadata": {"name": "verify-img"},
        "spec": {"validationFailureAction": "Enforce", "rules": [{
            "name": "check-sig",
            "match": {"any": [{"resources": {"kinds": ["Pod"]}}]},
            "verifyImages": [{
                "imageReferences": ["ghcr.io/org/*"],
                "attestors": [{"entries": [{"keys": {"publicKeys": key}}]}],
            }],
        }]},
    })
    cache = PolicyCache()
    cache.set(vi_policy)
    handlers = build_handlers(cache, registry_client=reg)
    req = {"request": {
        "uid": "u-iv", "operation": "CREATE", "namespace": "default",
        "object": {"apiVersion": "v1", "kind": "Pod",
                   "metadata": {"name": "p", "namespace": "default"},
                   "spec": {"containers": [
                       {"name": "c", "image": "ghcr.io/org/app:v1"}]}},
    }}
    out = handlers.mutate(req)
    assert out["response"]["allowed"] is True
    patch = json.loads(base64.b64decode(out["response"]["patch"]))
    values = [op.get("value") for op in patch]
    assert f"ghcr.io/org/app:v1@{digest}" in values

    # unverifiable image (wrong key in registry) => denied
    reg2 = StaticRegistry()
    reg2.add_image("ghcr.io/org/app:v1", digest)
    handlers2 = build_handlers(cache, registry_client=reg2)
    out2 = handlers2.mutate(req)
    assert out2["response"]["allowed"] is False


def test_audit_verify_images_does_not_block():
    """Audit-mode verifyImages failures must not deny admission
    (utils/block.go: only Enforce blocks)."""
    from kyverno_tpu.images import StaticRegistry

    vi_policy = ClusterPolicy.from_dict({
        "apiVersion": "kyverno.io/v1", "kind": "ClusterPolicy",
        "metadata": {"name": "verify-img-audit"},
        "spec": {"validationFailureAction": "Audit",
                 "rules": [{
                     "name": "check-sig",
                     "match": {"any": [{"resources": {"kinds": ["Pod"]}}]},
                     "verifyImages": [{
                         "imageReferences": ["ghcr.io/org/*"],
                         "mutateDigest": False,
                         "attestors": [{"entries": [{"keys": {
                             "publicKeys": "-----BEGIN PUBLIC KEY-----\nX\n-----END PUBLIC KEY-----"}}]}],
                     }],
                 }]},
    })
    cache = PolicyCache()
    cache.set(vi_policy)
    handlers = build_handlers(cache, registry_client=StaticRegistry())
    req = {"request": {
        "uid": "u-audit", "operation": "CREATE", "namespace": "default",
        "object": {"apiVersion": "v1", "kind": "Pod",
                   "metadata": {"name": "p", "namespace": "default"},
                   "spec": {"containers": [
                       {"name": "c", "image": "ghcr.io/org/app:v1"}]}},
    }}
    out = handlers.mutate(req)
    assert out["response"]["allowed"] is True


def test_audit_verify_images_lands_in_reports():
    """Audit verifyImages failures surface in the report aggregator
    even though admission is allowed."""
    from kyverno_tpu.cluster import ReportAggregator
    from kyverno_tpu.images import StaticRegistry

    vi_policy = ClusterPolicy.from_dict({
        "apiVersion": "kyverno.io/v1", "kind": "ClusterPolicy",
        "metadata": {"name": "verify-img-audit2"},
        "spec": {"validationFailureAction": "Audit",
                 "rules": [{
                     "name": "check-sig",
                     "match": {"any": [{"resources": {"kinds": ["Pod"]}}]},
                     "verifyImages": [{
                         "imageReferences": ["ghcr.io/org/*"],
                         "mutateDigest": False,
                         "attestors": [{"entries": [{"keys": {
                             "publicKeys": "-----BEGIN PUBLIC KEY-----\nX\n-----END PUBLIC KEY-----"}}]}],
                     }],
                 }]},
    })
    cache = PolicyCache()
    cache.set(vi_policy)
    agg = ReportAggregator()
    handlers = build_handlers(cache, aggregator=agg,
                              registry_client=StaticRegistry())
    req = {"request": {
        "uid": "u-audit2", "operation": "CREATE", "namespace": "default",
        "object": {"apiVersion": "v1", "kind": "Pod",
                   "metadata": {"name": "p2", "namespace": "default"},
                   "spec": {"containers": [
                       {"name": "c", "image": "ghcr.io/org/app:v1"}]}},
    }}
    out = handlers.mutate(req)
    assert out["response"]["allowed"] is True
    assert agg.summary().get("error", 0) + agg.summary().get("fail", 0) >= 1


# -- fine-grained per-policy routing + policy CR webhooks
# (server.go:296-300 fine-grained paths, handlers.go:200-240 scoping,
# /policyvalidate + /policymutate routes server.go:117-132)


def test_finegrained_validate_scopes_to_named_policy(server):
    # routed for the enforce policy: its failure blocks
    out = _post(server, "/validate/fail/finegrained/no-privileged",
                review(pod("fg-bad", True)))
    assert out["response"]["allowed"] is False
    # routed for the mutate-only policy: no-privileged's failure on the
    # same pod must NOT leak into the decision
    out = _post(server, "/validate/fail/finegrained/add-label",
                review(pod("fg-bad2", True)))
    assert out["response"]["allowed"] is True


def test_finegrained_unknown_policy_honors_failure_policy(server):
    out = _post(server, "/validate/fail/finegrained/no-such-policy",
                review(pod("fg-x", True)))
    assert out["response"]["allowed"] is False
    assert "not found" in out["response"]["status"]["message"]
    out = _post(server, "/validate/ignore/finegrained/no-such-policy",
                review(pod("fg-y", True)))
    assert out["response"]["allowed"] is True


def test_finegrained_mutate_scopes_to_named_policy(server):
    out = _post(server, "/mutate/fail/finegrained/add-label",
                review(pod("fg-m", None)))
    assert "patch" in out["response"]
    out = _post(server, "/mutate/fail/finegrained/no-privileged",
                review(pod("fg-m2", None)))
    assert "patch" not in out["response"]


def test_policy_cr_webhook_routes(server):
    ok = review(VALIDATE_POLICY, uid="pv1")
    out = _post(server, "/policyvalidate", ok)
    assert out["response"]["allowed"] is True
    bad = review({"apiVersion": "kyverno.io/v1", "kind": "ClusterPolicy",
                  "metadata": {"name": "empty"}, "spec": {"rules": []}},
                 uid="pv2")
    out = _post(server, "/policyvalidate", bad)
    assert out["response"]["allowed"] is False
    assert "no rules" in out["response"]["status"]["message"]
    out = _post(server, "/policymutate", ok)
    assert out["response"]["allowed"] is True


def test_webhookconfig_finegrained_path_matches_server_routes():
    """The controller-generated fine-grained URL must be a path the
    server actually scopes (round-4 finding: configs promised per-policy
    endpoints the server ignored)."""
    from kyverno_tpu.cluster.webhookconfig import (FINE_GRAINED_ANNOTATION,
                                                   WebhookConfigGenerator)

    p = json.loads(json.dumps(VALIDATE_POLICY))
    p["metadata"]["annotations"] = {FINE_GRAINED_ANNOTATION: "true"}
    cache = PolicyCache()
    cache.set(ClusterPolicy.from_dict(p))
    gen = WebhookConfigGenerator(cache)
    cfg = gen.build_validating()
    paths = [w["clientConfig"]["service"]["path"] for w in cfg["webhooks"]]
    assert "/validate/fail/finegrained/no-privileged" in paths, paths


def test_failure_policy_class_paths_filter_evaluation():
    """/validate/fail evaluates only Fail-class policies and
    /validate/ignore only Ignore-class (handlers.go:244 filterPolicies);
    the bare path is the unfiltered "all" class."""
    ignore_pol = json.loads(json.dumps(VALIDATE_POLICY))
    ignore_pol["metadata"]["name"] = "no-privileged-ignore"
    ignore_pol["spec"]["failurePolicy"] = "Ignore"
    cache = PolicyCache()
    cache.set(ClusterPolicy.from_dict(ignore_pol))
    handlers = build_handlers(cache, ClusterSnapshot(), ReportAggregator())
    bad = review(pod("cls", True))["request"]
    # fail path: the only policy is Ignore-class -> nothing evaluates
    out = handlers.validate({"request": bad}, "fail")
    assert out["response"]["allowed"] is True
    # ignore path and bare path both see it
    out = handlers.validate({"request": bad}, "ignore")
    assert out["response"]["allowed"] is False
    out = handlers.validate({"request": bad})
    assert out["response"]["allowed"] is False


def test_partial_evaluations_merge_in_reports():
    """Class-split and fine-grained paths cover disjoint policy sets;
    their report rows must merge per policy, not clobber per resource."""
    from kyverno_tpu.cluster.reports import ReportResult

    agg = ReportAggregator()
    mk = lambda pol, res: ReportResult(
        policy=pol, rule="r", result=res, resource_kind="Pod",
        resource_name="p", resource_namespace="default")
    agg.put("uid1", [mk("a", "fail"), mk("b", "pass")])
    agg.put("uid1", [mk("a", "pass")], scope={"a"})
    rows = {(r.policy, r.result) for r in agg._per_resource["uid1"]}
    assert rows == {("a", "pass"), ("b", "pass")}
